"""UIServer: embedded training dashboard.

Analog of the reference's PlayUIServer (deeplearning4j-play/.../
PlayUIServer.java:53, SURVEY §2.12): attach a StatsStorage, serve the
train overview (score chart, throughput), per-layer mean-magnitude
charts, system info, and receive remote-routed records
(RemoteReceiverModule analog at POST /remote). Zero dependencies: a
ThreadingHTTPServer + one self-contained HTML page drawing charts on a
<canvas>.
"""

from __future__ import annotations

import json
import logging
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import StatsStorage

# Upload cap for POST bodies (t-SNE coords / remote-routed records): the
# dashboard binds localhost, but an unbounded Content-Length read could
# still exhaust memory on a bad client.
_MAX_UPLOAD_BYTES = 8 << 20

_log = logging.getLogger(__name__)

_PAGE = """<!doctype html>
<html><head><title>deeplearning4j_tpu training UI</title><style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h2{margin:8px 0} .card{background:#fff;border:1px solid #ddd;
border-radius:6px;padding:12px;margin-bottom:14px}
canvas{width:100%;height:220px} td,th{padding:2px 10px;text-align:left}
nav a{margin-right:14px;text-decoration:none;color:#1668b8;
font-weight:bold} nav a.on{color:#111;border-bottom:2px solid #111}
.tab{display:none}.tab.on{display:block}
svg text{font:11px sans-serif} .node rect{fill:#eef;stroke:#88a}
img.act{image-rendering:pixelated;border:1px solid #ccc;margin:4px}
</style></head><body>
<nav id=nav>
<a href=#overview class=on>{{i18n:train.nav.overview}}</a>
<a href=#model>{{i18n:train.nav.model}}</a>
<a href=#system>{{i18n:train.nav.system}}</a>
<a href=#activations>{{i18n:train.nav.activations}}</a>
<a href=#tsne>{{i18n:train.nav.tsne}}</a>
<a href=#evaluation>{{i18n:train.nav.evaluation}}</a></nav>
<div id=overview class="tab on">
<h2>{{i18n:train.overview.title}}</h2>
<div class=card><b>{{i18n:train.overview.score}}</b><canvas id=score></canvas></div>
<div class=card><b>{{i18n:train.overview.throughput}}</b><canvas id=tput></canvas></div>
<div class=card><b>Per-layer mean |param|</b><canvas id=pm></canvas></div>
<div class=card><b>Session</b><table id=info></table></div>
</div>
<div id=model class=tab>
<h2>{{i18n:train.model.title}}</h2>
<div class=card><svg id=dag width="100%" height="500"></svg></div>
<div class=card><b>Layer detail</b> <span id=lname></span>
<table id=ldetail></table>
<b>mean |param| and mean |update| over iterations</b>
<canvas id=lseries></canvas>
<b>latest param / update / gradient histograms</b>
<canvas id=lhist style="height:140px"></canvas>
<canvas id=luhist style="height:140px"></canvas>
<canvas id=lghist style="height:140px"></canvas></div>
</div>
<div id=system class=tab>
<h2>{{i18n:train.system.title}}</h2>
<div class=card><b>Device memory (bytes in use)</b>
<canvas id=mem></canvas></div>
<div class=card><b>ETL ms / iteration</b><canvas id=etl></canvas></div>
</div>
<div id=activations class=tab>
<h2>{{i18n:train.activations.title}}</h2>
<div class=card>iteration:
<input type=range id=actslider min=0 max=0 step=1 value=0
style="width:60%">
<span id=actlabel>latest</span></div>
<div class=card id=actimgs>no activation records yet — attach a
ConvolutionalListener</div>
</div>
<div id=tsne class=tab>
<h2>t-SNE</h2>
<div class=card><canvas id=tsneplot style="height:480px"></canvas></div>
</div>
<div id=evaluation class=tab>
<h2>{{i18n:train.evaluation.title}}</h2>
<div class=card><b id=roctitle>ROC curve</b>
<canvas id=rocplot style="height:260px"></canvas></div>
<div class=card><b id=prtitle>Precision-recall curve</b>
<canvas id=prplot style="height:260px"></canvas></div>
<div class=card><b>Reliability diagram</b>
<canvas id=relplot style="height:260px"></canvas></div>
<div class=card><b id=phisttitle>Predicted probabilities</b>
<canvas id=probhist style="height:160px"></canvas></div>
</div>
<script>
function draw(cv, series, labels){
  const c = cv.getContext('2d');
  const W = cv.width = cv.clientWidth, H = cv.height = cv.clientHeight;
  c.clearRect(0,0,W,H);
  let vals = series.flat().filter(v=>isFinite(v));
  if(!vals.length) return;
  const lo = Math.min(...vals), hi = Math.max(...vals)||1;
  const colors=['#1668b8','#c2410c','#15803d','#7c3aed','#be123c',
                '#0e7490','#a16207','#4d7c0f'];
  series.forEach((s,si)=>{
    c.strokeStyle=colors[si%colors.length]; c.beginPath();
    s.forEach((v,i)=>{
      const x=i/(Math.max(s.length-1,1))*(W-40)+30;
      const y=H-15-(v-lo)/(hi-lo||1)*(H-30);
      i?c.lineTo(x,y):c.moveTo(x,y)});
    c.stroke();
    if(labels&&labels[si]){c.fillStyle=colors[si%colors.length];
      c.fillText(labels[si],35,12+12*si)}});
  c.fillStyle='#333';
  c.fillText(hi.toPrecision(4),2,12); c.fillText(lo.toPrecision(4),2,H-4);
}
function drawDag(nodes, stats){
  const svg = document.getElementById('dag');
  svg.replaceChildren();
  const pos = {}; const W = svg.clientWidth||900;
  const perRow = Math.max(2, Math.floor(W/170));
  nodes.forEach((n,i)=>{
    pos[n.name] = {x: 20+(i%perRow)*165, y: 20+Math.floor(i/perRow)*70};});
  const NS='http://www.w3.org/2000/svg';
  nodes.forEach(n=>{ (n.inputs||[]).forEach(src=>{
    if(!pos[src]) return;
    const l=document.createElementNS(NS,'line');
    l.setAttribute('x1',pos[src].x+75); l.setAttribute('y1',pos[src].y+40);
    l.setAttribute('x2',pos[n.name].x+75); l.setAttribute('y2',pos[n.name].y);
    l.setAttribute('stroke','#99a'); svg.append(l);});});
  nodes.forEach(n=>{
    const g=document.createElementNS(NS,'g'); g.setAttribute('class','node');
    const r=document.createElementNS(NS,'rect');
    r.setAttribute('x',pos[n.name].x); r.setAttribute('y',pos[n.name].y);
    r.setAttribute('width',150); r.setAttribute('height',40);
    r.setAttribute('rx',5);
    const t1=document.createElementNS(NS,'text');
    t1.setAttribute('x',pos[n.name].x+6); t1.setAttribute('y',pos[n.name].y+15);
    t1.textContent=n.name;
    const t2=document.createElementNS(NS,'text');
    t2.setAttribute('x',pos[n.name].x+6); t2.setAttribute('y',pos[n.name].y+31);
    t2.textContent=n.type+' ('+n.n_params+')';
    g.append(r,t1,t2);
    g.onclick=()=>{selectedLayer=n; drillDown(n);};
    svg.append(g);});
  svg.setAttribute('height', 20+Math.ceil(nodes.length/perRow)*70);
}
function drawBars(cv, hist, color){
  const c=cv.getContext('2d');
  const W=cv.width=cv.clientWidth, H=cv.height=cv.clientHeight;
  c.clearRect(0,0,W,H);
  if(!hist||!hist.counts||!hist.counts.length){
    c.fillText('no histogram yet',20,20); return;}
  const mx=Math.max(...hist.counts)||1, n=hist.counts.length;
  hist.counts.forEach((v,i)=>{
    c.fillStyle=color;
    const bw=(W-60)/n;
    c.fillRect(30+i*bw, H-18-(v/mx)*(H-34), bw-1, (v/mx)*(H-34));});
  c.fillStyle='#333';
  c.fillText(hist.min.toPrecision(3),30,H-4);
  c.fillText(hist.max.toPrecision(3),W-70,H-4);
}
async function drillDown(n){
  const st=latestStats[n.name]||{};
  document.getElementById('lname').textContent=n.name;
  const rows=Object.entries({name:n.name,type:n.type,
    params:n.n_params,...st}).map(([k,v])=>{
    const tr=document.createElement('tr');
    const th=document.createElement('th'); th.textContent=k;
    const td=document.createElement('td');
    td.textContent=JSON.stringify(v); tr.append(th,td); return tr;});
  document.getElementById('ldetail').replaceChildren(...rows);
  const ld=await (await fetch('api/layer?session='+dagSession
    +'&name='+encodeURIComponent(n.name))).json();
  draw(document.getElementById('lseries'),
       [ld.param_mean_magnitude||[], ld.update_mean_magnitude||[]],
       ['mean |param|','mean |update|']);
  drawBars(document.getElementById('lhist'), ld.param_histogram,
           '#1668b8');
  drawBars(document.getElementById('luhist'), ld.update_histogram,
           '#c2410c');
  drawBars(document.getElementById('lghist'), ld.grad_histogram,
           '#15803d');
}
function scatter(cv, pts, labels){
  const c=cv.getContext('2d');
  const W=cv.width=cv.clientWidth, H=cv.height=cv.clientHeight;
  c.clearRect(0,0,W,H);
  if(!pts.length) { c.fillText('POST /api/tsne or UIServer.upload_tsne()'
    ,20,20); return; }
  const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
  const lx=Math.min(...xs), hx=Math.max(...xs)||1;
  const ly=Math.min(...ys), hy=Math.max(...ys)||1;
  pts.forEach((p,i)=>{
    const x=(p[0]-lx)/(hx-lx||1)*(W-60)+30;
    const y=(p[1]-ly)/(hy-ly||1)*(H-40)+20;
    c.fillStyle='#1668b8'; c.fillRect(x-1.5,y-1.5,3,3);
    if(labels&&labels[i]) c.fillText(labels[i],x+4,y+3);});
}
function xyplot(cv, curves, labels, diag){
  // x-y curves on a [0,1]x[0,1] frame (ROC / PR / reliability)
  const c=cv.getContext('2d');
  const W=cv.width=cv.clientWidth, H=cv.height=cv.clientHeight;
  c.clearRect(0,0,W,H);
  const L=35,R=10,T=10,B=20;
  const px=x=>L+x*(W-L-R), py=y=>H-B-y*(H-T-B);
  c.strokeStyle='#ccc'; c.strokeRect(L,T,W-L-R,H-T-B);
  c.fillStyle='#333';
  c.fillText('0',L-8,H-B+12); c.fillText('1',W-R-6,H-B+12);
  c.fillText('1',L-12,T+8);
  if(diag){ c.strokeStyle='#ddd'; c.beginPath();
    c.moveTo(px(0),py(0)); c.lineTo(px(1),py(1)); c.stroke(); }
  const colors=['#1668b8','#c2410c','#15803d'];
  let any=false;
  curves.forEach((cur,si)=>{
    if(!cur||!cur.x||!cur.x.length) return; any=true;
    c.strokeStyle=colors[si%colors.length]; c.beginPath();
    cur.x.forEach((x,i)=>{const X=px(x),Y=py(cur.y[i]);
      i?c.lineTo(X,Y):c.moveTo(X,Y)});
    c.stroke();
    if(labels&&labels[si]){c.fillStyle=colors[si%colors.length];
      c.fillText(labels[si],L+8,T+14+12*si)}});
  if(!any){c.fillStyle='#333';
    c.fillText('UIServer.upload_evaluation(roc=..., calibration=...)',
               L+10,H/2);}
}
function showTab(){
  const h=(location.hash||'#overview').slice(1);
  document.querySelectorAll('.tab').forEach(d=>
    d.classList.toggle('on',d.id===h));
  document.querySelectorAll('nav a').forEach(a=>
    a.classList.toggle('on',a.hash==='#'+h));
}
window.onhashchange=()=>{showTab(); tick();};
let dagSession=null, latestStats={}, lastActIter=null,
    selectedLayer=null, actIters=[], actFollow=true;
document.addEventListener('DOMContentLoaded',()=>{
  const sl=document.getElementById('actslider');
  sl.oninput=async ()=>{
    actFollow = (+sl.value === actIters.length-1);
    const it = actIters[+sl.value];
    if(it===undefined) return;
    const sessions = await (await fetch('api/sessions')).json();
    const s = sessions[sessions.length-1];
    renderActs(await (await fetch('api/activations?session='+s
      +'&iteration='+it)).json(), false);
  };
});
function renderActs(act, updateSlider){
  const imgs = act.activations_png||{};
  actIters = act.iterations||[];
  const sl=document.getElementById('actslider');
  sl.max = Math.max(0, actIters.length-1);
  if(updateSlider && actFollow) sl.value = sl.max;
  document.getElementById('actlabel').textContent =
    'iteration '+(act.iteration??'—')+' ('+actIters.length+' recorded)';
  if(!Object.keys(imgs).length) return;
  if(act.iteration===lastActIter) return;
  lastActIter = act.iteration;
  const div=document.getElementById('actimgs');
  div.replaceChildren(...Object.entries(imgs).map(([name,b64])=>{
    const w=document.createElement('div');
    const lbl=document.createElement('b'); lbl.textContent=name;
    const img=document.createElement('img'); img.className='act';
    img.src='data:image/png;base64,'+b64;
    w.append(lbl,document.createElement('br'),img); return w;}));
}
async function tick(){
  showTab();
  const h=(location.hash||'#overview').slice(1);
  const sessions = await (await fetch('api/sessions')).json();
  if(!sessions.length) return;
  const s = sessions[sessions.length-1];
  if(h==='overview'){
    const d = await (await fetch('api/overview?session='+s)).json();
    draw(document.getElementById('score'), [d.scores]);
    draw(document.getElementById('tput'), [d.samples_per_sec]);
    const names = Object.keys(d.param_mean_magnitude||{});
    draw(document.getElementById('pm'),
         names.map(n=>d.param_mean_magnitude[n]), names);
    const info = d.static_info||{};
    const tbl = document.getElementById('info');
    tbl.replaceChildren(...Object.entries(info)
      .filter(([k,v])=>k!=='model_graph').map(([k,v])=>{
      const tr=document.createElement('tr');
      const th=document.createElement('th'); th.textContent=k;
      const td=document.createElement('td');
      td.textContent=JSON.stringify(v);
      tr.append(th,td); return tr;}));
  } else if(h==='model'){
    // the graph is static per session: build the SVG once (rebuilding
    // every tick would wipe it mid-click); stats refresh via reference
    const md = await (await fetch('api/model?session='+s)).json();
    Object.assign(latestStats, md.latest_param_stats||{});
    if(dagSession!==s){ drawDag(md.graph||[], latestStats);
                        dagSession=s; }
    if(selectedLayer) drillDown(selectedLayer);
  } else if(h==='system'){
    const sys = await (await fetch('api/system?session='+s)).json();
    const d = await (await fetch('api/overview?session='+s)).json();
    draw(document.getElementById('mem'), [sys.bytes_in_use||[]]);
    draw(document.getElementById('etl'), [d.etl_ms||[]]);
  } else if(h==='activations'){
    if(actFollow){
      renderActs(await (await fetch('api/activations?session='+s))
        .json(), true);
    }
  } else if(h==='tsne'){
    const ts = await (await fetch('api/tsne')).json();
    scatter(document.getElementById('tsneplot'), ts.points||[],
            ts.labels||[]);
  } else if(h==='evaluation'){
    const ev = await (await fetch('api/evaluation')).json();
    const roc = ev.roc, pr = ev.pr, rel = ev.reliability;
    if(roc) document.getElementById('roctitle').textContent =
      'ROC curve (AUC='+(ev.auc??0).toFixed(4)+')';
    xyplot(document.getElementById('rocplot'),
           [roc?{x:roc.fpr,y:roc.tpr}:null], ['ROC'], true);
    if(pr) document.getElementById('prtitle').textContent =
      'Precision-recall curve (AUPRC='+(ev.auprc??0).toFixed(4)+')';
    xyplot(document.getElementById('prplot'),
           [pr?{x:pr.recall,y:pr.precision}:null], ['PR'], false);
    xyplot(document.getElementById('relplot'),
           [rel?{x:rel.meanPredictedValueX,y:rel.fractionPositivesY}
               :null], ['reliability'], true);
    const ph = ev.probability_histogram;
    if(ph) drawBars(document.getElementById('probhist'),
      {counts:ph.binCounts,min:ph.lower,max:ph.upper}, '#1668b8');
  }
}
tick(); setInterval(tick, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "DL4JTpuUI/1.0"
    storage: StatsStorage = None   # set by UIServer
    modules: list = []             # registered UIModule instances
    modules_routes: list = []      # their merged Route list
    registry = None                # metrics registry for /healthz
    #                                (None -> the process default)

    def log_message(self, *a):   # silence request logging
        pass

    def _json(self, obj, code=200, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        u = urlparse(self.path)
        if u.path in ("/", "/train", "/train/overview"):
            from deeplearning4j_tpu.ui.i18n import I18N
            q = parse_qs(u.query)
            lang = q.get("lang", [None])[0]
            body = I18N.get_instance().render(_PAGE, lang).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/metrics":
            # Prometheus text exposition of the process-wide registry
            # (observe/registry.py): training loops publish here via
            # TelemetryCollector / RecompileWatchdog
            from deeplearning4j_tpu.observe import default_registry
            body = default_registry().render().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/healthz":
            # liveness + degradation: the verdict comes from the metrics
            # registry (observe/health.py) — NaN storm, recompile storm
            # or replica divergence turn the probe into a 503 with the
            # reasons spelled out, while /metrics stays a plain scrape
            from deeplearning4j_tpu.observe.health import health_status
            health = health_status(self.registry)
            health["sessions"] = (len(self.storage.list_session_ids())
                                  if self.storage is not None else 0)
            self._json(health,
                       200 if health["status"] == "ok" else 503)
            return
        if u.path == "/api/i18n":
            from deeplearning4j_tpu.ui.i18n import I18N
            q = parse_qs(u.query)
            lang = q.get("lang", [None])[0]
            i18n = I18N.get_instance()
            self._json({"language": i18n.resolve_language(lang),
                        "languages": i18n.languages(),
                        "messages": i18n.messages(lang)})
            return
        if u.path == "/api/sessions":
            self._json(self.storage.list_session_ids())
            return
        if u.path == "/api/overview":
            q = parse_qs(u.query)
            sess = q.get("session", [None])[0]
            if not sess:
                ids = self.storage.list_session_ids()
                sess = ids[-1] if ids else None
            self._json(self._overview(sess))
            return
        if u.path == "/api/updates":
            q = parse_qs(u.query)
            sess = q.get("session", [None])[0]
            self._json(self.storage.get_all_updates(sess) if sess else [])
            return
        if u.path == "/api/model":
            sess = self._session(u)
            static = (self.storage.get_static_info(sess) or {}) if sess \
                else {}
            latest = {}
            for up in reversed(self.storage.get_all_updates(sess)
                               if sess else []):
                if up.get("param_stats"):
                    latest = {k: {kk: vv for kk, vv in v.items()
                                  if kk != "histogram"}
                              for k, v in up["param_stats"].items()}
                    break
            self._json({"graph": static.get("model_graph", []),
                        "latest_param_stats": latest})
            return
        if u.path == "/api/system":
            sess = self._session(u)
            ups = self.storage.get_all_updates(sess) if sess else []
            self._json({
                "bytes_in_use": [
                    (up.get("memory") or {}).get("bytes_in_use") or 0
                    for up in ups if "memory" in up],
                "static_info": (self.storage.get_static_info(sess) or {})
                if sess else {},
            })
            return
        if u.path == "/api/activations":
            sess = self._session(u)
            q = parse_qs(u.query)
            want = q.get("iteration", [None])[0]
            ups = [up for up in (self.storage.get_all_updates(sess)
                                 if sess else [])
                   if up.get("type") == "activations"]
            iters = [up.get("iteration") for up in ups]
            chosen = None
            if want is not None:
                chosen = next((up for up in ups
                               if str(up.get("iteration")) == want), None)
            if chosen is None and ups:
                chosen = ups[-1]
            self._json({
                "iterations": iters,
                "iteration": chosen.get("iteration") if chosen else None,
                "activations_png": (chosen.get("activations_png", {})
                                    if chosen else {}),
            })
            return
        if u.path == "/api/layer":
            # per-layer drill-down: param/update stats over time + the
            # latest histograms (the TrainModule per-layer charts)
            sess = self._session(u)
            q = parse_qs(u.query)
            name = q.get("name", [None])[0]
            its, pmag, pstd, umag, ratio = [], [], [], [], []
            phist = uhist = ghist = None
            for up in (self.storage.get_all_updates(sess)
                       if sess else []):
                ps = (up.get("param_stats") or {}).get(name)
                if not ps:
                    continue
                its.append(up.get("iteration"))
                pmag.append(ps.get("mean_magnitude"))
                pstd.append(ps.get("stdev"))
                us = (up.get("update_stats") or {}).get(name) or {}
                um = us.get("mean_magnitude")
                umag.append(um)
                pm = ps.get("mean_magnitude")
                # um may legitimately be 0.0 (frozen layer): keep it
                ratio.append((um / pm) if um is not None and pm
                             else None)
                phist = ps.get("histogram") or phist
                uhist = us.get("histogram") or uhist
                gs = (up.get("grad_stats") or {}).get(name) or {}
                ghist = gs.get("histogram") or ghist
            self._json({
                "name": name, "iterations": its,
                "param_mean_magnitude": pmag, "param_stdev": pstd,
                "update_mean_magnitude": umag, "update_ratio": ratio,
                "param_histogram": phist, "update_histogram": uhist,
                "grad_histogram": ghist,
            })
            return
        if u.path == "/api/tsne":
            self._json(getattr(self.server, "tsne_data", None)
                       or {"points": [], "labels": []})
            return
        if u.path == "/api/evaluation":
            self._json(getattr(self.server, "evaluation_data", None)
                       or {})
            return
        route = self._match_module_route("GET", u.path)
        if route is not None:
            self._run_module_route(route, u, None)
            return
        self._json({"error": "not found"}, 404)

    def _match_module_route(self, method: str, path: str):
        """The ONE place route matching happens (404-before-body in
        do_POST and dispatch both use it)."""
        for route in self.modules_routes:
            if route.method == method and route.path == path:
                return route
        return None

    def _run_module_route(self, route, u, body) -> None:
        """Dispatch to a registered UIModule route (the UIModule.java
        SPI); built-in routes have already had their chance, so core
        paths cannot be shadowed. A ``DeadlineExceeded`` escaping the
        handler answers **504** with ``{"error": "deadline"}`` — the
        request's budget ran out, which is neither a module bug (500)
        nor an overload shed (503)."""
        from deeplearning4j_tpu.parallel.deadline import DeadlineExceeded
        from deeplearning4j_tpu.ui.modules import UIModuleContext
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        ctx = UIModuleContext(storage=self.storage, server=self.server,
                              headers=self.headers)
        status = 200
        extra_headers = None
        stream = None
        try:
            chaos = getattr(self.server, "chaos_request", None)
            if chaos is not None:
                chaos.fail(arg=u.path)
            out = route.handler(ctx, q, body)
            if self._is_stream(out):
                # generator/iterator payload: stream it as SSE below,
                # outside this try — once headers go out, a producer
                # error can't become a 500 JSON anyway
                stream = out
                payload = ctype = None
            elif isinstance(out, tuple) and len(out) == 3 \
                    and isinstance(out[0], dict):
                # (dict, headers_or_None, status): JSON with an
                # explicit HTTP status and optional extra headers —
                # the fleet router's 503-on-shed path (Retry-After)
                out, extra_headers, status = out
                payload, ctype = None, None
            elif isinstance(out, tuple):
                payload, ctype = out[:2]
                if len(out) == 3:
                    status = int(out[2])
                if isinstance(payload, str):
                    payload = payload.encode("utf-8")
                payload = bytes(payload)
            elif isinstance(out, dict):
                payload, ctype = None, None
            else:
                # a handler returning anything else is a module bug;
                # surface it as one instead of a 200 with JSON null
                raise TypeError(
                    "module route handler must return a dict or a "
                    f"(payload, content_type) tuple, got "
                    f"{type(out).__name__}")
        except DeadlineExceeded:
            self._json({"error": "deadline", "reason": "deadline"}, 504)
            return
        except Exception as e:                # module bug ≠ server crash
            # full detail stays in the server log; HTTP clients only
            # learn the exception class (no message text leaks)
            _log.exception("module route %s %s failed",
                           route.method, route.path)
            self._json({"error": "module route failed: "
                                 f"{type(e).__name__}"}, 500)
            return
        if stream is not None:
            self._send_event_stream(stream)
            return
        if payload is not None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        else:
            self._json(out, status, extra_headers)

    @staticmethod
    def _is_stream(out) -> bool:
        """A module route payload that should stream: any iterable that
        is not one of the fixed return forms (dict / tuple / str /
        bytes / list). Covers generators and stream objects exposing
        ``__iter__`` (e.g. GenerationStream)."""
        return (not isinstance(out, (dict, tuple, list, str, bytes))
                and (hasattr(out, "__next__") or hasattr(out, "__iter__")))

    def _send_event_stream(self, events):
        """Stream a module route's generator/iterator payload as
        Server-Sent Events. The response stays HTTP/1.0 with
        ``Connection: close`` — no Content-Length, EOF delimits the
        stream — so long-lived token streams need no chunked-framing
        change to every other route. Each yielded item becomes one
        ``data:`` event (dicts are JSON-encoded, strings pass through).

        Drain correctness: this runs inside ``_do_post``, so the
        server's active_requests counter covers the stream's whole
        lifetime — a drain() lets in-flight streams finish (PR 11's
        contract) while the drain gate 503s new ones.
        """
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        try:
            for ev in events:
                data = ev if isinstance(ev, str) else json.dumps(ev)
                for line in data.splitlines() or [""]:
                    self.wfile.write(b"data: " + line.encode("utf-8")
                                     + b"\n")
                self.wfile.write(b"\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream; closing the generator below
            # lets the producer cancel its sequence
            _log.info("event-stream client disconnected: %s %s",
                      self.command, self.path)
        except Exception:
            _log.exception("event-stream producer failed mid-stream")
            try:
                self.wfile.write(b"event: error\ndata: "
                                 b"{\"error\": \"stream failed\"}\n\n")
                self.wfile.flush()
            except OSError:
                pass
        finally:
            close = getattr(events, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    _log.exception("event-stream close() failed")

    def _session(self, u) -> Optional[str]:
        q = parse_qs(u.query)
        sess = q.get("session", [None])[0]
        if not sess:
            ids = self.storage.list_session_ids()
            sess = ids[-1] if ids else None
        return sess

    def _read_json_body(self):
        """Parse the POST body, enforcing the upload cap (negative
        Content-Length would make ``read(-1)`` slurp to EOF — reject it
        with the oversize case). Returns None after sending a 413."""
        n = int(self.headers.get("Content-Length", 0))
        if n < 0 or n > _MAX_UPLOAD_BYTES:
            self._json({"error": f"bad payload size ({n} bytes; "
                        f"cap {_MAX_UPLOAD_BYTES})"}, 413)
            return None
        body = self.rfile.read(n) or b"{}"
        # binary stats codec (the router's wire format) or JSON
        from deeplearning4j_tpu.ui.codec import (
            decode_stats_record, is_stats_record)
        if is_stats_record(body):
            return decode_stats_record(body)
        return json.loads(body)

    def do_POST(self):
        path = urlparse(self.path).path
        if getattr(self.server, "draining", False) \
                and path in getattr(self.server, "drain_paths",
                                    ("/api/predict", "/api/generate")):
            # graceful drain: stop ADMITTING new work; requests already
            # inside _do_post — including long-lived token streams —
            # keep running to completion (tracked by active_requests,
            # which drain() waits on)
            self._json({"error": "draining"}, 503,
                       {"Retry-After": "1"})
            return
        lock = getattr(self.server, "active_lock", None)
        if lock is None:
            self._do_post(path)
            return
        with lock:
            self.server.active_requests += 1
        try:
            self._do_post(path)
        finally:
            with lock:
                self.server.active_requests -= 1

    def _do_post(self, path):
        if path == "/api/tsne":
            # TsneModule analog: upload 2-D coordinates (+labels) to plot
            try:
                payload = self._read_json_body()
                if payload is None:
                    return
                pts = payload.get("points", [])
                if not all(isinstance(p, (list, tuple)) and len(p) == 2
                           for p in pts):
                    raise ValueError("points must be [x, y] pairs")
                coords = [[float(a), float(b)] for a, b in pts]
                if not all(math.isfinite(a) and math.isfinite(b)
                           for a, b in coords):
                    raise ValueError("points must be finite numbers")
                self.server.tsne_data = {
                    "points": coords,
                    "labels": [str(l) for l in payload.get("labels", [])],
                }
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._json({"error": str(e)}, 400)
                return
            self._json({"ok": True})
            return
        if path == "/api/evaluation":
            # curve-object upload (the reference UI charts RocCurve etc.
            # produced by eval; curves arrive as their to_dict forms)
            try:
                payload = self._read_json_body()
                if payload is None:
                    return
                if not isinstance(payload, dict):
                    raise ValueError("expected a JSON object of curves")
                self.server.evaluation_data = payload
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._json({"error": str(e)}, 400)
                return
            self._json({"ok": True})
            return
        # RemoteReceiverModule analog: accept remote-routed records
        if path != "/remote":
            u = urlparse(self.path)
            # match the route BEFORE touching the body: a routing miss
            # must 404, not 400 on an unparseable probe payload
            route = self._match_module_route("POST", u.path)
            if route is None:
                self._json({"error": "not found"}, 404)
                return
            try:
                body = self._read_json_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._json({"error": str(e)}, 400)
                return
            if body is None:
                return
            self._run_module_route(route, u, body)
            return
        try:
            payload = self._read_json_body()
            if payload is None:
                return
            record = payload.get("record", {})
            if "session_id" not in record:
                raise ValueError("record missing session_id")
            if payload.get("kind") == "static":
                self.storage.put_static_info(record)
            else:
                self.storage.put_update(record)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._json({"error": str(e)}, 400)
            return
        for m in self.modules:              # UIModule.reportStorageEvents
            try:
                m.on_update(record)
            except Exception:               # module bug ≠ stored-record
                pass                        # failure or server crash
        self._json({"ok": True})

    def _overview(self, session_id: Optional[str]) -> dict:
        if not session_id:
            return {}
        ups = self.storage.get_all_updates(session_id)
        pm: dict = {}
        for u in ups:
            for lname, st in (u.get("param_stats") or {}).items():
                pm.setdefault(lname, []).append(st.get("mean_magnitude"))
        return {
            "session": session_id,
            "iterations": [u.get("iteration") for u in ups],
            "scores": [u.get("score") for u in ups],
            "samples_per_sec": [u.get("samples_per_sec") or 0.0
                                for u in ups],
            "etl_ms": [u.get("etl_ms") for u in ups],
            "param_mean_magnitude": pm,
            "static_info": self.storage.get_static_info(session_id),
        }


class UIServer:
    """reference: api/UIServer.getInstance().attach(statsStorage). Serves
    on localhost; ``url`` gives the address for RemoteUIStatsStorageRouter
    peers."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000, registry=None):
        self.port = port
        self.storage: Optional[StatsStorage] = None
        # registry backing /healthz degradation checks; None uses the
        # process-wide default (tests pass isolated registries)
        self.registry = registry
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._modules: List = []

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance

    def attach(self, storage: StatsStorage):
        self.storage = storage
        if self._httpd is not None:
            self._httpd.RequestHandlerClass.storage = storage
        for m in self._modules:
            m.on_attach(storage)
        return self

    def register_module(self, module):
        """Plug a UIModule into the dashboard (reference:
        PlayUIServer's uiModules list — custom modules merge their
        routes; built-in paths cannot be shadowed)."""
        from deeplearning4j_tpu.ui.modules import UIModule
        if not isinstance(module, UIModule):
            raise TypeError(f"expected a UIModule, got {type(module)}")
        self._modules.append(module)
        if self.storage is not None:
            module.on_attach(self.storage)
        if self._httpd is not None:
            h = self._httpd.RequestHandlerClass
            h.modules = list(self._modules)
            h.modules_routes = [r for m in self._modules
                                for r in m.get_routes()]
        return self

    def start(self):
        if self._httpd is not None:
            return self
        if self.storage is None:
            raise RuntimeError(
                "attach(stats_storage) before start() — the UI has "
                "nothing to serve otherwise")
        handler = type("BoundHandler", (_Handler,),
                       {"storage": self.storage,
                        "registry": self.registry,
                        "modules": list(self._modules),
                        "modules_routes": [r for m in self._modules
                                           for r in m.get_routes()]})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                          handler)
        # drain bookkeeping lives on the httpd (handlers see it as
        # self.server): draining gates /api/predict admission, and
        # active_requests counts POST handlers still running so drain()
        # can wait for responses to finish SERIALIZING, not just for
        # the engine queue to empty
        self._httpd.draining = False
        self._httpd.drain_paths = {"/api/predict", "/api/generate",
                                   "/api/neighbors",
                                   "/api/neighbors/shard"}
        self._httpd.active_requests = 0
        self._httpd.active_lock = threading.Lock()
        # fault injection on the ingress edge (chaos/plan.py site
        # "ui.request"): resolved ONCE here — None when disarmed, so
        # per-request dispatch pays a single attribute probe
        from deeplearning4j_tpu.chaos.hook import chaos_site
        self._httpd.chaos_request = chaos_site("ui.request")
        self.port = self._httpd.server_address[1]   # resolves port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def upload_tsne(self, points, labels=None):
        """Populate the t-SNE tab (the reference UI's TsneModule accepts
        coordinate uploads; manifold/tsne.py output plugs in directly)."""
        import numpy as np
        pts = np.asarray(points, np.float64)
        if pts.ndim != 2 or pts.shape[1] != 2:
            raise ValueError(f"expected (N, 2) coords, got {pts.shape}")
        if self._httpd is None:
            raise RuntimeError("start() the server first")
        self._httpd.tsne_data = {
            "points": pts.tolist(),
            # `labels or []` would crash on numpy label arrays
            "labels": [] if labels is None else [str(l) for l in labels],
        }
        return self

    def upload_evaluation(self, roc=None, calibration=None):
        """Populate the Evaluation tab from a ``ROC`` and/or an
        ``EvaluationCalibration`` accumulator — their eval/curves
        exports (RocCurve, PrecisionRecallCurve, ReliabilityDiagram,
        probability Histogram) drive the charts, the analog of the
        reference UI consuming eval/curves objects."""
        if self._httpd is None:
            raise RuntimeError("start() the server first")
        data = {}
        if roc is not None:
            rc = roc.get_roc_curve()
            pr = roc.get_precision_recall_curve()
            data.update(roc=rc.to_dict(), pr=pr.to_dict(),
                        auc=rc.calculate_auc(),
                        auprc=pr.calculate_auprc())
        if calibration is not None:
            data.update(
                reliability=calibration.get_reliability_diagram()
                .to_dict(),
                probability_histogram=calibration
                .get_probability_histogram().to_dict(),
                residual_histogram=calibration
                .get_residual_histogram().to_dict(),
                ece=calibration.expected_calibration_error())
        self._httpd.evaluation_data = data
        return self

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def drain(self):
        """Stop admitting ingress requests (``drain_paths``, by default
        /api/predict and /api/generate — they get 503 + Retry-After);
        everything already in flight, including long-lived token
        streams, keeps running. Idempotent; ``active_requests`` reports
        what is left."""
        if self._httpd is not None:
            self._httpd.draining = True
        return self

    @property
    def active_requests(self) -> int:
        """POST handlers currently executing (admitted before any
        drain). 0 once every accepted request has fully responded."""
        httpd = self._httpd
        if httpd is None:
            return 0
        with httpd.active_lock:
            return httpd.active_requests

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
