"""TPU-native nearest-neighbor retrieval serving.

The reference framework ships retrieval as a host-side product: a
VPTree behind a Play REST app (deeplearning4j-nearestneighbor-server —
SURVEY §2.10), O(corpus) pointer-chasing Python/Java per query. Here
the corpus lives on the device as a sharded matrix and one jitted
kernel per (query-bucket, shard, k, precision) does the whole query:
distance matmul + in-graph ``lax.top_k``, so only (k indices, k
distances) ever cross the host boundary.

- :mod:`kernels` — the fused distance+top-k kernels (f32 / int8 brute
  force, IVF-routed variants).
- :mod:`index` — ShardedCorpusIndex: build / quantize / IVF-cluster /
  save / load over the ArtifactStore bucket layout.
- :mod:`engine` — RetrievalEngine: AOT-style warmup sweep, bucket and
  k ladders, host-side k-way merge, recompile watchdog, hot index
  promotion.
- :mod:`cluster` — RetrievalNode (gossiped shard ownership) and
  NeighborsDispatcher (scatter-gather fan-out with partial-result
  degradation).
"""

from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex


def __getattr__(name):
    # cluster pulls in the ui/http stack; keep `import retrieval` light
    if name in ("RetrievalNode", "NeighborsDispatcher",
                "PartialResultError"):
        from deeplearning4j_tpu.retrieval import cluster
        return getattr(cluster, name)
    raise AttributeError(name)


__all__ = ["RetrievalEngine", "ShardedCorpusIndex", "RetrievalNode",
           "NeighborsDispatcher", "PartialResultError"]
