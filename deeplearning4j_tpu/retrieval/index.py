"""ShardedCorpusIndex: build / quantize / cluster / persist a corpus.

The corpus matrix [N, D] is split into fixed-geometry shards of
``shard_rows`` rows (the last shard zero-padded, padding rows carrying
``+inf`` norm and id -1 so the kernels can never surface them). Fixed
geometry is the zero-recompile contract: every shard of an index — and
every shard of any *refreshed* version of it — dispatches through the
same compiled executables, so an index refresh while serving costs no
compiles.

Per shard, eagerly precomputed at build (never on the query path):

- **row norms** ``c2`` — the ``|c|²`` half of the expanded-quadratic
  distance; for int8 computed from the DEQUANTIZED rows so the kernel's
  distance algebra is self-consistent.
- **int8 arm** — per-row symmetric quantization via
  ``ops/quantize.quantize_rows`` (host numpy: two processes building
  the same corpus produce bitwise-identical shards).
- **IVF layout** — k-means centroids (``clustering/kmeans`` on a
  seeded subsample), then a capacity-BALANCED assignment: every row
  lands in its nearest centroid with free capacity (preference order by
  distance), capacity ``M = ceil(alpha · rows / K)``. Balancing keeps
  the padded [K, M, D] cluster-major layout dense (α bounds the padding
  waste) and — unlike truncating overfull clusters — drops no rows, so
  the recall gate measures routing loss only.

Persistence rides the ArtifactStore bucket layout
(``parallel/aot_cache.ArtifactStore``): one ``.npz`` per shard under
``objects/<key>/``, versioned filenames, and a ``neighbors.json``
manifest written atomically LAST — publish is a manifest flip, readers
mid-save just keep the previous version (the AOT cache's own
discipline, no locks).
"""

from __future__ import annotations

import json
import math
import os
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.ops.quantize import quantize_rows

INDEX_MANIFEST = "neighbors.json"


class IndexShard:
    """One device-shard's arrays (numpy at build/load; the engine moves
    them on-device once and drops the host copies)."""

    def __init__(self, shard_id: int, n: int, vectors, c2, ids,
                 row_scales=None, centroids=None, clustered=None,
                 c_scales=None, c_c2=None, c_ids=None, refine=None):
        self.shard_id = int(shard_id)
        self.n = int(n)                      # real (non-padding) rows
        self.vectors = vectors               # [R, D] f32 | int8
        self.c2 = c2                         # [R] f32, +inf padding
        self.ids = ids                       # [R] int32, -1 padding
        self.row_scales = row_scales         # [R] f32 (int8 arm)
        self.centroids = centroids           # [K, D] f32 (IVF)
        self.clustered = clustered           # [K, M, D] (IVF)
        self.c_scales = c_scales             # [K, M] f32 (IVF int8)
        self.c_c2 = c_c2                     # [K, M] f32, +inf padding
        self.c_ids = c_ids                   # [K, M] int32, -1 padding
        # int8 arm only: the original f32 rows [n, D], HOST-resident
        # for the exact rescore of the device's int8 candidates —
        # never moved to the accelerator, so the 4x HBM density of the
        # int8 shard is kept while recall is recovered by refining a
        # 2k-deep candidate list against full precision
        self.refine = refine

    @property
    def has_ivf(self) -> bool:
        return self.centroids is not None


def _balanced_assign(x: np.ndarray, centroids: np.ndarray,
                     cap: int) -> List[np.ndarray]:
    """Capacity-balanced cluster assignment: rows claim centroids in
    preference order (nearest first) until one has free capacity.
    Greedy order is by each row's best distance, so contended clusters
    keep their closest members and spill their fringe. Returns the row
    indices per cluster (each ≤ cap; total == len(x))."""
    k = centroids.shape[0]
    # chunked [N, K] distances: the full matrix for 1M×256 f32 would be
    # 1 GB; 64k-row chunks keep the build under ~70 MB of scratch
    prefs = np.empty((x.shape[0], k), np.int32)
    best = np.empty(x.shape[0], np.float32)
    for lo in range(0, x.shape[0], 65536):
        hi = min(lo + 65536, x.shape[0])
        d2 = (np.sum(x[lo:hi] ** 2, axis=1, keepdims=True)
              - 2.0 * (x[lo:hi] @ centroids.T)
              + np.sum(centroids ** 2, axis=1)[None, :])
        order = np.argsort(d2, axis=1, kind="stable")
        prefs[lo:hi] = order
        best[lo:hi] = np.take_along_axis(
            d2, order[:, :1], axis=1)[:, 0]
    members: List[List[int]] = [[] for _ in range(k)]
    free = np.full(k, cap, np.int64)
    for row in np.argsort(best, kind="stable"):
        for c in prefs[row]:
            if free[c] > 0:
                members[c].append(row)
                free[c] -= 1
                break
        else:                                # cap·K ≥ N by construction
            raise AssertionError("balanced assignment ran out of "
                                 "capacity; alpha too small")
    return [np.asarray(m, np.int64) for m in members]  # host-sync-ok: build-time cluster membership lists (host build path)


def _fit_centroids(x: np.ndarray, k: int, seed: int,
                   max_iterations: int, sample: int) -> np.ndarray:
    """K-means centroids on a seeded subsample (Lloyd over the full
    shard buys nothing for routing quality once the sample covers the
    density; the subsample bounds build time on 1M-row shards)."""
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
    if x.shape[0] > sample:
        rng = np.random.default_rng(seed)
        x = x[rng.choice(x.shape[0], sample, replace=False)]
    km = KMeansClustering(k, max_iterations=max_iterations, seed=seed)
    km.fit(x)
    return np.asarray(km.cluster_centers_, np.float32)  # host-sync-ok: build-time kmeans centroids, once per build


class ShardedCorpusIndex:
    """The built (or loaded) index: shard list + geometry metadata."""

    def __init__(self, shards: List[IndexShard], *, dim: int,
                 shard_rows: int, precision: str, n_total: int,
                 version: str = "v1",
                 ivf: Optional[Dict[str, int]] = None, seed: int = 0,
                 all_shard_ids: Optional[List[int]] = None):
        self.shards = shards
        self.dim = int(dim)
        self.shard_rows = int(shard_rows)
        self.precision = precision
        self.n_total = int(n_total)
        self.version = str(version)
        self.ivf = dict(ivf) if ivf else None   # {"clusters", "cap"}
        self.seed = int(seed)
        # the PUBLISHED index's full shard universe (a node loading a
        # slice still gossips how many shards exist cluster-wide)
        self.all_shard_ids = (list(all_shard_ids)
                              if all_shard_ids is not None
                              else [s.shard_id for s in shards])

    # ---- construction ----------------------------------------------------
    @classmethod
    def build(cls, corpus: np.ndarray, *, shard_rows: int = 262144,
              precision: str = "f32", ivf_clusters: int = 0,
              ivf_alpha: float = 1.25, nprobe_hint: int = 8,
              kmeans_iterations: int = 20, kmeans_sample: int = 65536,
              version: str = "v1", seed: int = 0
              ) -> "ShardedCorpusIndex":
        if precision not in ("f32", "int8"):
            raise ValueError(f"precision must be f32|int8, "
                             f"got {precision!r}")
        corpus = np.ascontiguousarray(corpus, np.float32)
        n, dim = corpus.shape
        if n == 0:
            raise ValueError("empty corpus")
        shard_rows = min(int(shard_rows), _next_pow2(n))
        n_shards = max(1, math.ceil(n / shard_rows))
        ivf_meta = None
        if ivf_clusters:
            k = int(ivf_clusters)
            cap = math.ceil(ivf_alpha * shard_rows / k)
            ivf_meta = {"clusters": k, "cap": cap,
                        "nprobe_hint": int(nprobe_hint)}
        shards = []
        for s in range(n_shards):
            rows = corpus[s * shard_rows:(s + 1) * shard_rows]
            base = s * shard_rows
            shards.append(cls._build_shard(
                s, rows, base, shard_rows, precision, ivf_meta,
                kmeans_iterations, kmeans_sample, seed))
        return cls(shards, dim=dim, shard_rows=shard_rows,
                   precision=precision, n_total=n, version=version,
                   ivf=ivf_meta, seed=seed)

    @staticmethod
    def _build_shard(shard_id: int, rows: np.ndarray, base: int,
                     shard_rows: int, precision: str,
                     ivf: Optional[Dict[str, int]],
                     kmeans_iterations: int, kmeans_sample: int,
                     seed: int) -> IndexShard:
        n, dim = rows.shape
        ids = np.full(shard_rows, -1, np.int32)
        ids[:n] = np.arange(base, base + n, dtype=np.int32)
        if precision == "int8":
            q, scales = quantize_rows(rows)
            deq = q.astype(np.float32) * scales[:, None]
            vectors = np.zeros((shard_rows, dim), np.int8)
            vectors[:n] = q
            row_scales = np.ones(shard_rows, np.float32)
            row_scales[:n] = scales
            real_c2 = np.sum(deq * deq, axis=1)
        else:
            vectors = np.zeros((shard_rows, dim), np.float32)
            vectors[:n] = rows
            row_scales = None
            real_c2 = np.sum(rows * rows, axis=1)
        c2 = np.full(shard_rows, np.inf, np.float32)
        c2[:n] = real_c2
        shard = IndexShard(shard_id, n, vectors, c2, ids,
                           row_scales=row_scales,
                           refine=(np.ascontiguousarray(
                               rows, np.float32)
                               if precision == "int8" else None))
        if ivf is not None:
            k, cap = ivf["clusters"], ivf["cap"]
            centroids = _fit_centroids(
                rows, min(k, max(1, n)), seed + shard_id,
                kmeans_iterations, kmeans_sample)
            if centroids.shape[0] < k:       # degenerate small shard
                pad = np.zeros((k - centroids.shape[0], dim),
                               np.float32)
                centroids = np.concatenate([centroids, pad])
            members = _balanced_assign(rows, centroids, cap)
            cl_shape = (k, cap, dim)
            clustered = np.zeros(
                cl_shape, np.int8 if precision == "int8"
                else np.float32)
            c_scales = np.ones((k, cap), np.float32) \
                if precision == "int8" else None
            c_c2 = np.full((k, cap), np.inf, np.float32)
            c_ids = np.full((k, cap), -1, np.int32)
            for c, m in enumerate(members):
                t = len(m)
                if t == 0:
                    continue
                clustered[c, :t] = vectors[m]
                c_c2[c, :t] = c2[m]
                c_ids[c, :t] = ids[m]
                if c_scales is not None:
                    c_scales[c, :t] = row_scales[m]
            shard.centroids = centroids
            shard.clustered = clustered
            shard.c_scales = c_scales
            shard.c_c2 = c_c2
            shard.c_ids = c_ids
        return shard

    # ---- persistence -----------------------------------------------------
    def save(self, store, key: str) -> str:
        """Persist under the store's bucket layout and publish by
        flipping the manifest LAST (atomic tmp+rename). Returns the
        manifest path."""
        d = store.cache_dir(key)
        entries = []
        for sh in self.shards:
            fname = f"nn-{self.version}-shard{sh.shard_id}.npz"
            arrays = {"vectors": sh.vectors, "c2": sh.c2,
                      "ids": sh.ids}
            for attr in ("row_scales", "centroids", "clustered",
                         "c_scales", "c_c2", "c_ids", "refine"):
                v = getattr(sh, attr)
                if v is not None:
                    arrays[attr] = v
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, os.path.join(d, fname))
            entries.append({"id": sh.shard_id, "file": fname,
                            "n": sh.n})
        manifest = {"version": self.version, "dim": self.dim,
                    "shard_rows": self.shard_rows,
                    "precision": self.precision,
                    "n_total": self.n_total, "seed": self.seed,
                    "ivf": self.ivf, "shards": entries}
        path = os.path.join(d, INDEX_MANIFEST)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, store, key: str, *,
             shard_ids: Optional[List[int]] = None
             ) -> "ShardedCorpusIndex":
        """Load the published version; ``shard_ids`` restricts to this
        node's assigned shards (the scatter-gather placement)."""
        d = store.cache_dir(key)
        path = os.path.join(d, INDEX_MANIFEST)
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError):
            raise FileNotFoundError(
                f"no published neighbors index under {d!r}")
        shards = []
        for e in m["shards"]:
            if shard_ids is not None and e["id"] not in shard_ids:
                continue
            with np.load(os.path.join(d, e["file"])) as z:
                a: Dict[str, Any] = {k: z[k] for k in z.files}
            shards.append(IndexShard(
                e["id"], e["n"], a["vectors"], a["c2"], a["ids"],
                row_scales=a.get("row_scales"),
                centroids=a.get("centroids"),
                clustered=a.get("clustered"),
                c_scales=a.get("c_scales"), c_c2=a.get("c_c2"),
                c_ids=a.get("c_ids"), refine=a.get("refine")))
        if not shards:
            raise ValueError(
                f"no shards matched {shard_ids!r} in index {key!r} "
                f"(have {[e['id'] for e in m['shards']]})")
        return cls(shards, dim=m["dim"], shard_rows=m["shard_rows"],
                   precision=m["precision"], n_total=m["n_total"],
                   version=m["version"], ivf=m.get("ivf"),
                   seed=m.get("seed", 0),
                   all_shard_ids=[e["id"] for e in m["shards"]])

    @staticmethod
    def published_version(store, key: str) -> Optional[str]:
        d = store.cache_dir(key)
        try:
            with open(os.path.join(d, INDEX_MANIFEST)) as f:
                return json.load(f).get("version")
        except (OSError, json.JSONDecodeError):
            return None

    # ---- geometry --------------------------------------------------------
    def geometry(self) -> Dict[str, Any]:
        """The compile-relevant shape signature: two indexes with equal
        geometry dispatch through the same executables, which is what
        hot promotion checks before swapping."""
        return {"dim": self.dim, "shard_rows": self.shard_rows,
                "precision": self.precision,
                "ivf": {k: self.ivf[k] for k in ("clusters", "cap")}
                if self.ivf else None}

    @property
    def shard_ids(self) -> List[int]:
        return [s.shard_id for s in self.shards]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p
