"""RetrievalEngine: warmed fused-kernel serving over a sharded index.

The serving contract mirrors the predict engine's (parallel/serving.py):

- **Ladders, not live shapes.** Query batches pad up the pow2 bucket
  ladder; ``k`` pads up the configured k-ladder (a request for k=7
  runs the warmed k=10 executable and slices). Every (bucket, k, mode)
  cell is dispatched once by :meth:`warmup` — shards share one padded
  geometry, so the cell count is independent of shard count — and the
  recompile watchdog holds the zero-live-compile contract afterwards
  (``assert_warm``).
- **Only k leaves the device.** Per (query batch, shard) the host
  receives k ids + k distances; the cross-shard k-way merge is host
  numpy over S·k candidates, sorted by ``(distance, id)`` so tie order
  — and therefore the full response — is bitwise-deterministic
  run-to-run.
- **int8 refine.** The int8 arm overfetches to the ladder rung >= 2k
  on device, then exact-rescores those candidates against f32 source
  rows kept in HOST ram (FAISS IndexRefineFlat idiom): accelerator
  HBM holds only the 4x-dense int8 shard, and the recall the 8-bit
  ordering loses at depth k is recovered from the 2k candidate set.
- **Hot index promotion.** :meth:`refresh` loads the store's published
  version, gates it (recall@10 of the routed arm against the new
  index's own brute-force answers on seeded probes — routing loss, the
  thing a bad refresh regresses), and swaps the device arrays under the
  lock. Geometry equality is checked first: a refreshed index reuses
  the warmed executables, zero recompiles (the ISSUE's PR 10-style
  gated promotion).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.recompile import RecompileWatchdog
from deeplearning4j_tpu.observe.registry import default_registry
from deeplearning4j_tpu.parallel.deadline import Deadline
from deeplearning4j_tpu.retrieval import kernels
from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex


class _DeviceShard:
    """One shard's device-resident arrays (host copies dropped)."""

    def __init__(self, shard):
        self.shard_id = shard.shard_id
        self.n = shard.n
        self.vectors = jnp.asarray(shard.vectors)
        self.c2 = jnp.asarray(shard.c2)
        self.ids = jnp.asarray(shard.ids)
        self.row_scales = (jnp.asarray(shard.row_scales)
                           if shard.row_scales is not None else None)
        self.centroids = (jnp.asarray(shard.centroids)
                          if shard.centroids is not None else None)
        self.clustered = (jnp.asarray(shard.clustered)
                          if shard.clustered is not None else None)
        self.c_scales = (jnp.asarray(shard.c_scales)
                         if shard.c_scales is not None else None)
        self.c_c2 = (jnp.asarray(shard.c_c2)
                     if shard.c_c2 is not None else None)
        self.c_ids = (jnp.asarray(shard.c_ids)
                      if shard.c_ids is not None else None)


def merge_topk(dists: np.ndarray, ids: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Host k-way merge of per-source candidates: ``dists``/``ids`` are
    [S, B, k'] — concat the source axis, order by ``(distance, id)``
    (the id tie-break makes cross-source ties deterministic regardless
    of arrival order), drop padding (id < 0), take k. Returns
    ([B, k] f32, [B, k] int32) padded with (+inf, -1) when fewer than k
    real candidates exist."""
    s, b, kk = dists.shape
    flat_d = np.transpose(dists, (1, 0, 2)).reshape(b, s * kk)
    flat_i = np.transpose(ids, (1, 0, 2)).reshape(b, s * kk)
    # padding sorts last: +inf distance, and id -1 remapped past every
    # real id so lexsort never prefers it on a distance tie
    tie = np.where(flat_i < 0, np.iinfo(np.int32).max, flat_i)
    order = np.lexsort((tie, flat_d), axis=1)[:, :k]
    out_d = np.take_along_axis(flat_d, order, axis=1)
    out_i = np.take_along_axis(flat_i, order, axis=1)
    out_d = np.where(out_i < 0, np.inf, out_d).astype(np.float32)
    return out_d, out_i.astype(np.int32)


class RetrievalEngine:
    """Fused distance+top-k serving over one node's index shards."""

    def __init__(self, index: ShardedCorpusIndex, *,
                 k_ladder: Optional[Tuple[int, ...]] = None,
                 max_batch: int = 64,
                 nprobe: Optional[int] = None,
                 registry=None, session_id: str = "neighbors",
                 tuned_config=None):
        from deeplearning4j_tpu.optimize.autotune import (
            resolve_tuned, tuned_value)
        self.registry = registry if registry is not None \
            else default_registry()
        self.session_id = session_id
        self.tuned_config = tuned_config
        self._lock = threading.Lock()
        self._inflight = 0
        self.max_batch = int(max_batch)
        self.buckets = _pow2_ladder(self.max_batch)
        k_ladder = resolve_tuned(k_ladder, tuned_config,
                                 "retrieval.k_ladder")
        self.k_ladder = tuple(sorted(int(k) for k in k_ladder))
        if not self.k_ladder or self.k_ladder[0] < 1:
            raise ValueError(f"bad k ladder {k_ladder!r}")
        self.modes = ["brute"] + (["ivf"] if index.ivf else [])
        if index.ivf:
            # explicit nprobe > machine-measured tuned value > the
            # index build's own geometry hint. The registry default (a
            # scalar) deliberately does NOT apply here: absent any
            # measurement, the per-index hint knows the geometry better
            if nprobe is None:
                nprobe = tuned_value("retrieval.nprobe", tuned_config)
            hint = index.ivf.get("nprobe_hint", 8)
            self.nprobe = min(int(nprobe or hint),
                              index.ivf["clusters"])
        else:
            self.nprobe = None
        self.default_mode = "ivf" if index.ivf else "brute"
        self._install(index)

        self.watchdog = RecompileWatchdog(
            registry=self.registry, session_id=session_id)
        self.query_ring = LatencyRing()
        self.merge_ring = LatencyRing()
        self.warmup_seconds: Optional[float] = None
        self._warm = False
        reg = self.registry
        self._c_queries = reg.counter(
            "dl4j_nn_queries_total",
            "nearest-neighbor queries answered (query vectors, not "
            "HTTP requests), per search mode")
        self._c_refresh = reg.counter(
            "dl4j_nn_index_refresh_total",
            "hot index promotions; outcome=promoted|rejected|noop")
        self._g_vectors = reg.gauge(
            "dl4j_nn_index_vectors",
            "corpus vectors in the full published index this engine "
            "serves a slice of")
        self._g_merge = reg.gauge(
            "dl4j_nn_merge_seconds",
            "host-side k-way merge wall time of the last query batch")
        self._g_vectors.set(float(index.n_total))  # host-sync-ok: python int metadata to gauge

    def _install(self, index: ShardedCorpusIndex):
        self.index = index
        self.dim = index.dim
        self.precision = index.precision
        self.version = index.version
        self.shard_ids = list(index.shard_ids)
        self.all_shard_ids = list(index.all_shard_ids)
        self._shards = [_DeviceShard(s) for s in index.shards]
        # int8 arm: the f32 rows stay in HOST ram (never shipped to
        # the accelerator) so the 2k-deep int8 candidate list can be
        # rescored at full precision — global ids are contiguous per
        # shard, so (base id, rows) is the whole lookup
        self._refine: Dict[int, Tuple[int, np.ndarray]] = {}
        for s in index.shards:
            if s.refine is not None:
                self._refine[s.shard_id] = (
                    int(np.asarray(s.ids)[0]),  # host-sync-ok: one-time install: refine rows are host f32 by design (int8 exact rescore source)
                    np.asarray(s.refine, np.float32))  # host-sync-ok: one-time install: refine rows are host f32 by design (int8 exact rescore source)
        # drop the remaining host copies: the device arrays are the
        # only resident corpus from here on (the index object keeps
        # only geometry metadata for promotion checks)
        for s in index.shards:
            s.vectors = s.c2 = s.ids = s.row_scales = None
            s.centroids = s.clustered = s.c_scales = None
            s.c_c2 = s.c_ids = s.refine = None

    # ---- dispatch --------------------------------------------------------
    def _dispatch(self, q_dev, sh: _DeviceShard, k: int, mode: str):
        """One (padded query batch, shard) kernel call. The watchdog
        key pins one ladder cell — (mode, precision, bucket, k) — so
        exactly one signature per key is the expected first compile
        and anything else (dtype drift, a ragged batch escaping the
        pad) counts as a live recompile."""
        key = (f"nn.{mode}.{self.precision}"
               f".b{q_dev.shape[0]}.k{k}")
        self.watchdog.observe(key, q_dev, k)
        if mode == "ivf":
            if sh.centroids is None:
                raise ValueError("index built without IVF layout")
            if self.precision == "int8":
                return kernels.ivf_topk_int8(
                    q_dev, sh.centroids, sh.clustered, sh.c_scales,
                    sh.c_c2, sh.c_ids, k, self.nprobe)
            return kernels.ivf_topk_f32(
                q_dev, sh.centroids, sh.clustered, sh.c_c2, sh.c_ids,
                k, self.nprobe)
        if self.precision == "int8":
            return kernels.brute_topk_int8(
                q_dev, sh.vectors, sh.row_scales, sh.c2, sh.ids, k)
        return kernels.brute_topk_f32(
            q_dev, sh.vectors, sh.c2, sh.ids, k)

    def _pad_k(self, k: int) -> int:
        for kk in self.k_ladder:
            if kk >= k:
                return kk
        raise ValueError(
            f"k={k} above the warmed ladder {self.k_ladder}; raise "
            f"k_ladder at engine construction")

    def _device_k(self, k: int) -> int:
        """The rung the DEVICE kernel runs at. The int8 arm overfetches
        to the next rung >= 2k when the ladder has one: the int8
        top-2k survives quantization where the int8 top-k ordering does
        not, and the exact f32 rescore of those candidates recovers
        full recall (the FAISS refine idiom). Falls back to plain
        rung(k) when the ladder tops out — rescore then only reorders."""
        if self._refine:
            for kk in self.k_ladder:
                if kk >= 2 * k:
                    return kk
        return self._pad_k(k)

    def _rescore(self, q: np.ndarray, cand_d: np.ndarray,
                 cand_i: np.ndarray, k: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact-f32 rescore of the device's int8 candidates against
        the host-resident source rows, then (distance, id) re-sort to
        k. Host cost is O(B * k_dev * D) on k_dev rows per query —
        the candidate egress, not a corpus scan."""
        b, kk = cand_i.shape
        flat = cand_i.ravel()
        rows = np.zeros((flat.size, self.dim), np.float32)
        valid = flat >= 0
        for base, rr in self._refine.values():
            m = valid & (flat >= base) & (flat < base + rr.shape[0])
            if m.any():
                rows[m] = rr[flat[m] - base]
        d2 = ((q[:, None, :] - rows.reshape(b, kk, self.dim)) ** 2
              ).sum(-1).astype(np.float32)
        d2 = np.where(cand_i < 0, np.inf, d2)
        tie = np.where(cand_i < 0, np.iinfo(np.int32).max, cand_i)
        order = np.lexsort((tie, d2), axis=1)[:, :k]
        out_d = np.take_along_axis(d2, order, axis=1)
        out_i = np.take_along_axis(cand_i, order, axis=1)
        out_d = np.where(out_i < 0, np.inf, out_d).astype(np.float32)
        return out_d, out_i.astype(np.int32)

    def _pad_bucket(self, b: int) -> int:
        for bb in self.buckets:
            if bb >= b:
                return bb
        return self.buckets[-1]

    def search(self, queries, k: int, *, mode: Optional[str] = None,
               deadline: Optional[Deadline] = None,
               shard_ids: Optional[List[int]] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Answer ``queries`` ([D] or [B, D]) with the k nearest
        neighbors over this engine's (or the ``shard_ids`` subset's)
        shards. Returns ``(distances [B, k] f32, ids [B, k] int32)``
        — padded with (+inf, -1) when the corpus holds fewer than k.
        Batches over ``max_batch`` chunk internally."""
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        mode = mode or self.default_mode
        if mode not in self.modes:
            raise ValueError(f"mode {mode!r} not in {self.modes}")
        q = np.asarray(queries, np.float32)  # host-sync-ok: ingress decode — queries arrive as host JSON/numpy
        single = q.ndim == 1
        if single:
            q = q[None, :]
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries must be [B, {self.dim}], got {q.shape}")
        with self._lock:
            self._inflight += 1
            shards = self._shards if shard_ids is None else \
                [s for s in self._shards if s.shard_id in shard_ids]
        try:
            if not shards:
                raise ValueError(f"no local shards in {shard_ids!r}")
            if deadline is not None:
                deadline.check("neighbors: before dispatch")
            t0 = time.perf_counter()
            k_dev = self._device_k(k)
            out_d, out_i = [], []
            for lo in range(0, q.shape[0], self.max_batch):
                chunk = q[lo:lo + self.max_batch]
                b = chunk.shape[0]
                bucket = self._pad_bucket(b)
                if bucket > b:
                    chunk = np.concatenate(
                        [chunk, np.zeros((bucket - b, self.dim),
                                         np.float32)])
                q_dev = jnp.asarray(chunk)
                per = []
                for sh in shards:
                    if deadline is not None:
                        deadline.check("neighbors: mid fan-out")
                    per.append(self._dispatch(q_dev, sh, k_dev, mode))
                # fetch AFTER every shard dispatched: XLA overlaps the
                # shard kernels; one sync point per chunk
                d = np.stack([np.asarray(p[0]) for p in per])  # host-sync-ok: the k-results egress — the (k ids, k distances) fetch IS the query answer
                i = np.stack([np.asarray(p[1]) for p in per])  # host-sync-ok: the k-results egress (ids half)
                tm0 = time.perf_counter()
                if self._refine:
                    # keep the full k_dev candidate depth through the
                    # merge, then refine to k at exact f32
                    md, mi = merge_topk(d[:, :b], i[:, :b], k_dev)
                    md, mi = self._rescore(chunk[:b], md, mi, k)
                else:
                    md, mi = merge_topk(d[:, :b], i[:, :b], k)
                self.merge_ring.record(time.perf_counter() - tm0)
                out_d.append(md)
                out_i.append(mi)
            dists = np.concatenate(out_d)
            ids = np.concatenate(out_i)
            dt = time.perf_counter() - t0
            self.query_ring.record(dt)
            self._g_merge.set(self.merge_ring.quantiles((0.5,))[0.5]
                              if self.merge_ring.count else 0.0)
            self._c_queries.inc(float(q.shape[0]), mode=mode)  # host-sync-ok: python int batch size to counter
            if single:
                return dists[0], ids[0]
            return dists, ids
        finally:
            with self._lock:
                self._inflight -= 1

    # ---- warmup / recompile contract -------------------------------------
    def warmup(self) -> "RetrievalEngine":
        """Dispatch every (bucket, k, mode) cell once over every local
        shard and block, so no live query pays a compile. Idempotent."""
        t0 = time.perf_counter()
        for mode in self.modes:
            for bucket in self.buckets:
                q_dev = jnp.zeros((bucket, self.dim), jnp.float32)
                for kk in self.k_ladder:
                    last = None
                    for sh in self._shards:
                        last = self._dispatch(q_dev, sh, kk, mode)
                    if last is not None:
                        last[0].block_until_ready()
        self._warm = True
        self.warmup_seconds = time.perf_counter() - t0
        return self

    @property
    def recompiles_after_warmup(self) -> int:
        return self.watchdog.count()

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def assert_warm(self):
        n = self.watchdog.count()
        if n:
            raise AssertionError(
                f"retrieval engine saw {n} recompile(s) after warmup: "
                f"{self.watchdog.events[-3:]}")

    # ---- hot index promotion ---------------------------------------------
    def refresh(self, store, key: str, *, probe_queries: int = 64,
                recall_floor: float = 0.95,
                recall_k: int = 10) -> Dict[str, Any]:
        """Load the store's published index version and hot-promote it.

        Gated: the candidate must (a) match the serving geometry — the
        warmed executables must keep fitting, zero recompiles — and
        (b) pass recall@``recall_k`` ≥ ``recall_floor`` of its routed
        arm (IVF when built, else brute) against its own exact
        brute-force answers on seeded probe queries. A candidate that
        fails either gate is rejected and the current version keeps
        serving."""
        new = ShardedCorpusIndex.load(store, key,
                                      shard_ids=self.shard_ids)
        if new.version == self.version:
            self._c_refresh.inc(1.0, outcome="noop")
            return {"promoted": False, "reason": "same version",
                    "version": self.version}
        if new.geometry() != self.index.geometry():
            self._c_refresh.inc(1.0, outcome="rejected")
            return {"promoted": False, "reason":
                    f"geometry mismatch: serving "
                    f"{self.index.geometry()}, candidate "
                    f"{new.geometry()}", "version": self.version}
        recall = _self_recall(new, n_queries=probe_queries,
                              k=recall_k)
        if recall is not None and recall < recall_floor:
            self._c_refresh.inc(1.0, outcome="rejected")
            return {"promoted": False, "reason":
                    f"recall@{recall_k} {recall:.3f} < gate "
                    f"{recall_floor}", "version": self.version}
        old = self.version
        with self._lock:
            self._install(new)
        # the warmed executables key on shapes only — same geometry,
        # same executables; re-observe nothing
        self._c_refresh.inc(1.0, outcome="promoted")
        self._g_vectors.set(float(new.n_total))  # host-sync-ok: python int metadata to gauge
        return {"promoted": True, "from": old,
                "version": self.version,
                "recall_gate": recall}

    # ---- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        q = {f"p{int(k * 100)}": v * 1e3
             for k, v in self.query_ring.quantiles().items()}
        return {
            "session": self.session_id,
            "index_version": self.version,
            "precision": self.precision,
            "modes": list(self.modes),
            "default_mode": self.default_mode,
            "nprobe": self.nprobe,
            "dim": self.dim,
            "vectors_total": self.index.n_total,
            "shards": self.shard_ids,
            "all_shards": self.all_shard_ids,
            "shard_rows": self.index.shard_rows,
            "k_ladder": list(self.k_ladder),
            "buckets": list(self.buckets),
            "refine": bool(self._refine),
            "queries": self.query_ring.count,
            "latency_ms": q,
            "merge_p50_ms": (self.merge_ring.quantiles((0.5,))[0.5]
                             * 1e3 if self.merge_ring.count else None),
            "inflight": self.inflight,
            "warm": self._warm,
            "warmup_s": self.warmup_seconds,
            "recompiles_after_warmup": self.recompiles_after_warmup,
        }

    def shutdown(self):
        """API symmetry with the serving engines (the fleet router and
        node drain call it); no worker threads to stop here."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def _self_recall(index: ShardedCorpusIndex, *, n_queries: int,
                 k: int) -> Optional[float]:
    """Recall@k of the index's routed arm against its own exact
    brute-force answers, on seeded probes drawn from the corpus rows
    (plus noise) — host numpy, no compiles, measures ROUTING loss
    (quantization loss needs the f32 source and is gated by the
    correctness harness / benchmark instead). None when the index has
    no routed arm to gate."""
    if not index.ivf:
        return None
    rng = np.random.default_rng(index.seed + 0x5eed)
    rows, c2s, idss = [], [], []
    for sh in index.shards:
        v = np.asarray(sh.vectors)  # host-sync-ok: refresh-gate host emulation, off the query path
        if v.dtype == np.int8:
            v = v.astype(np.float32) * np.asarray(  # host-sync-ok: refresh-gate host emulation, off the query path
                sh.row_scales)[:, None]
        rows.append(v[:sh.n])
        idss.append(np.asarray(sh.ids)[:sh.n])  # host-sync-ok: refresh-gate host emulation, off the query path
    corpus = np.concatenate(rows)
    ids = np.concatenate(idss)
    take = rng.choice(corpus.shape[0],
                      min(n_queries, corpus.shape[0]), replace=False)
    q = corpus[take] + rng.normal(
        0, 1e-3, (len(take), corpus.shape[1])).astype(np.float32)
    # exact: full distance, top-k by (d, id)
    d2 = (np.sum(q ** 2, axis=1, keepdims=True)
          - 2.0 * (q @ corpus.T) + np.sum(corpus ** 2, axis=1)[None])
    kk = min(k, corpus.shape[0])
    exact = ids[np.argsort(d2, axis=1, kind="stable")[:, :kk]]
    # routed: per-shard IVF emulation on host (same centroids/layout)
    hits = 0
    probe = min(index.ivf.get("nprobe_hint", 8),
                index.ivf["clusters"])
    routed_d, routed_i = [], []
    for sh in index.shards:
        cd2 = (np.sum(q ** 2, axis=1, keepdims=True)
               - 2.0 * (q @ np.asarray(sh.centroids).T)  # host-sync-ok: refresh-gate host emulation, off the query path
               + np.sum(np.asarray(sh.centroids) ** 2, axis=1)[None])  # host-sync-ok: refresh-gate host emulation, off the query path
        probes = np.argsort(cd2, axis=1, kind="stable")[:, :probe]
        cl = np.asarray(sh.clustered)  # host-sync-ok: refresh-gate host emulation, off the query path
        if cl.dtype == np.int8:
            cl = cl.astype(np.float32) \
                * np.asarray(sh.c_scales)[..., None]  # host-sync-ok: refresh-gate host emulation, off the query path
        cc2 = np.asarray(sh.c_c2)  # host-sync-ok: refresh-gate host emulation, off the query path
        cids = np.asarray(sh.c_ids)  # host-sync-ok: refresh-gate host emulation, off the query path
        for qi in range(q.shape[0]):
            sub = cl[probes[qi]].reshape(-1, q.shape[1])
            sd2 = (np.sum(q[qi] ** 2) - 2.0 * (sub @ q[qi])
                   + cc2[probes[qi]].reshape(-1))
            sids = cids[probes[qi]].reshape(-1)
            order = np.argsort(sd2, kind="stable")[:kk]
            routed_d.append(sd2[order])
            routed_i.append(sids[order])
    s = len(index.shards)
    routed_d = np.asarray(routed_d, np.float32).reshape(  # host-sync-ok: refresh-gate host emulation, off the query path
        s, q.shape[0], -1)
    routed_i = np.asarray(routed_i, np.int32).reshape(  # host-sync-ok: refresh-gate host emulation, off the query path
        s, q.shape[0], -1)
    _, got = merge_topk(routed_d, routed_i, kk)
    for qi in range(q.shape[0]):
        hits += len(set(exact[qi]) & set(got[qi][got[qi] >= 0]))
    return hits / float(exact.size)  # host-sync-ok: python int ratio, refresh gate


def _pow2_ladder(top: int) -> Tuple[int, ...]:
    out, b = [], 1
    while b < top:
        out.append(b)
        b <<= 1
    out.append(top)
    return tuple(out)
