"""Cluster-wide retrieval: gossiped shard ownership + scatter-gather.

Placement is gossip, not a coordinator: every :class:`RetrievalNode`
heartbeats its owned shard ids (and the index's full shard universe)
into the shared :class:`NodeRegistry`, exactly like the serving nodes
gossip load. A :class:`NeighborsDispatcher` reads the registry
snapshot, groups the universe by owner, and fans one POST
``/api/neighbors/shard`` out per owning node through the
:class:`RemoteDispatcher` machinery — per-node circuit breakers,
deadline-capped transport timeouts, and the ``remote.send`` chaos seam
all come along for free. Each node answers its shards' merged top-k;
the dispatcher k-way-merges the node answers host-side by
``(distance, id)``.

Degradation is partial, never silent: when a shard's owners all fail
mid-query (SIGKILL, breaker open, shed), the dispatcher retries the
missing shards once on surviving replicas and then answers from
whatever shards responded with ``partial: True`` and the answered/total
shard counts — every in-flight query gets an answer, flagged when the
corpus slice behind it was incomplete (the chaos-soak contract).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.parallel.deadline import Deadline
from deeplearning4j_tpu.parallel.node import (
    NODE_DRAINING,
    NODE_UP,
    NodeRegistry,
)
from deeplearning4j_tpu.parallel.remote import (
    RemoteDispatcher,
    RemoteError,
)
from deeplearning4j_tpu.retrieval.engine import merge_topk

SHARD_PATH = "/api/neighbors/shard"


class RetrievalNode:
    """One retrieval worker: RetrievalEngine behind the fleet front
    door + UI HTTP surface, heartbeating shard ownership into the
    registry. The lifecycle contract mirrors ServingNode: ``drain()``
    gossips ``draining``, refuses new neighbor queries with 503 +
    Retry-After, finishes admitted in-flight searches, deregisters,
    then stops; ``install_sigterm_drain`` from parallel/node.py works
    unchanged."""

    def __init__(self, engine, *, node_id: str,
                 registry: NodeRegistry, pool_name: str = "neighbors",
                 slo_ms: Optional[float] = None, ui_port: int = 0,
                 heartbeat_interval_s: float = 0.5,
                 metrics_registry=None,
                 window_s: Optional[float] = None,
                 store=None, index_key: Optional[str] = None):
        from deeplearning4j_tpu.observe.registry import \
            default_registry
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        from deeplearning4j_tpu.ui.neighbors_module import \
            NeighborsModule
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

        self.node_id = str(node_id)
        self.registry = registry
        self.pool_name = pool_name
        self.engine = engine
        self.metrics = metrics_registry if metrics_registry is not None \
            else default_registry()
        self.heartbeat_interval_s = float(heartbeat_interval_s)  # host-sync-ok: python config scalar
        # warm BEFORE the first heartbeat: a node only becomes
        # dispatchable once every ladder cell holds a ready executable
        # (a rejoiner's compiles hit the persistent XLA cache when the
        # serve CLI armed it — fast, and still zero LIVE compiles)
        engine.warmup()
        self.router = FleetRouter(
            slo_ms=slo_ms, registry=self.metrics, window_s=window_s,
            session_id=f"nn-node-{self.node_id}")
        self.pool = self.router.add_retrieval_pool(
            pool_name, engine, slo_ms=slo_ms)
        self.server = UIServer(port=ui_port, registry=self.metrics)
        self.server.attach(InMemoryStatsStorage())
        self.server.register_module(NeighborsModule(
            router=self.router, model=pool_name, store=store,
            index_key=index_key))
        self.server.start()

        self._lock = threading.Lock()
        self._state = NODE_UP
        self._stopped = False
        self._stop_beat = threading.Event()
        self._beat_now()            # visible before the thread spins up
        self._beat_thread = threading.Thread(
            target=self._beat_loop,
            name=f"dl4j-nn-node-{self.node_id}", daemon=True)
        self._beat_thread.start()

    # ---- gossip ---------------------------------------------------------
    @property
    def url(self) -> str:
        return self.server.url

    def node_stats(self) -> Dict[str, Any]:
        """The gossiped snapshot: load (dispatcher tie-break) PLUS
        shard ownership (the scatter-gather placement map)."""
        pool = self.pool
        with pool.lock:
            pending = pool.pending
            p99 = pool.windowed_p99_ms
        return {"pending": pending,
                "inflight": self.engine.inflight,
                "windowed_p99_ms": p99,
                "requests": pool.ring.count,
                "shards": list(self.engine.shard_ids),
                "all_shards": list(self.engine.all_shard_ids),
                "index_version": self.engine.version}

    def _beat_now(self):
        with self._lock:
            state = self._state
        try:
            stats = self.node_stats()
        except Exception:
            stats = {}
        self.registry.write(self.node_id, self.url, state=state,
                            stats=stats)

    def _beat_loop(self):
        while not self._stop_beat.wait(self.heartbeat_interval_s):
            self._beat_now()

    # ---- convenience ----------------------------------------------------
    def search(self, queries, k: int, **kw):
        return self.router.neighbors(queries, k,
                                     model=self.pool_name, **kw)

    def assert_warm(self):
        self.router.assert_warm()

    def stats(self) -> Dict[str, Any]:
        return {"node_id": self.node_id, "url": self.url,
                "state": self._state, **self.router.stats()}

    # ---- lifecycle ------------------------------------------------------
    def _inflight_total(self) -> int:
        with self.pool.lock:
            pending = self.pool.pending
        return pending + self.server.active_requests

    def drain(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        t0 = time.monotonic()
        with self._lock:
            already = self._stopped
            self._state = NODE_DRAINING
        if already:
            return {"drained": True, "seconds": 0.0,
                    "inflight_left": 0}
        self._beat_now()                    # gossip "draining" at once
        self.server.drain()                 # 503 + Retry-After on new work
        deadline = t0 + float(timeout_s)  # host-sync-ok: python config scalar
        left = self._inflight_total()
        while left > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
            left = self._inflight_total()
        seconds = time.monotonic() - t0
        self._stop_beat.set()
        self._beat_thread.join(
            timeout=5 * self.heartbeat_interval_s + 1)
        self.registry.deregister(self.node_id)
        with self._lock:
            self._stopped = True
        self.server.stop()
        self.router.shutdown()
        return {"drained": left == 0, "seconds": seconds,
                "inflight_left": left}

    def shutdown(self):
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._stop_beat.set()
        self._beat_thread.join(
            timeout=5 * self.heartbeat_interval_s + 1)
        self.registry.deregister(self.node_id)
        self.server.stop()
        self.router.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class PartialResultError(RuntimeError):
    """Raised only under ``require_full=True``: some shard had no
    surviving owner. Default behavior degrades instead of raising."""


class NeighborsDispatcher:
    """Client-side scatter-gather over the gossiped shard map."""

    def __init__(self, registry: NodeRegistry, *,
                 dispatcher: Optional[RemoteDispatcher] = None,
                 timeout_s: float = 30.0,
                 max_fanout_workers: int = 16,
                 metrics=None, **dispatcher_kwargs):
        from deeplearning4j_tpu.observe.registry import \
            default_registry
        self.registry = registry
        self._rd = dispatcher if dispatcher is not None else \
            RemoteDispatcher(registry, timeout_s=timeout_s,
                             metrics=metrics, **dispatcher_kwargs)
        self._owns_rd = dispatcher is None
        # chaos seam: the soak kills a shard owner mid-query by failing
        # its fan-out leg here (on top of the transport-level
        # remote.send site the RemoteDispatcher already arms)
        self._chaos_fanout = chaos_site("neighbors.fanout")
        self._pool = ThreadPoolExecutor(
            max_workers=max_fanout_workers,
            thread_name_prefix="dl4j-nn-fanout")
        self.merge_ring = LatencyRing()
        reg = metrics if metrics is not None else default_registry()
        self._c_shard_req = reg.counter(
            "dl4j_nn_shard_requests_total",
            "per-node shard fan-out legs; outcome=ok|error")
        self._c_partial = reg.counter(
            "dl4j_nn_partial_total",
            "queries answered with partial:true — some shard had no "
            "surviving owner inside the budget")
        self._g_fanout = reg.gauge(
            "dl4j_nn_fanout_nodes",
            "owning nodes the last query fanned out to")
        self._g_merge = reg.gauge(
            "dl4j_nn_fanout_merge_seconds",
            "host-side cross-node k-way merge wall time, last query")

    # ---- placement -------------------------------------------------------
    def shard_map(self) -> Tuple[Dict[int, List[Dict[str, Any]]],
                                 List[int]]:
        """(shard -> owner records, full shard universe) from the
        current registry snapshot. The universe is the union of the
        gossiped ``all_shards`` (any single surviving node knows the
        published index's full extent)."""
        owners: Dict[int, List[Dict[str, Any]]] = {}
        universe: set = set()
        for rec in self._rd.records():
            stats = rec.get("stats") or {}
            shards = stats.get("shards")
            if not shards:
                continue
            universe.update(stats.get("all_shards") or shards)
            for s in shards:
                owners.setdefault(int(s), []).append(rec)
        return owners, sorted(universe)

    # ---- one fan-out leg -------------------------------------------------
    def _leg(self, rec: Dict[str, Any], shards: List[int],
             payload: Dict[str, Any],
             deadline: Optional[Deadline]) -> Dict[str, Any]:
        if self._chaos_fanout is not None:
            self._chaos_fanout.fail(arg=rec["node_id"])
        body = dict(payload, shards=shards)
        out = self._rd.call(rec, body, path=SHARD_PATH,
                            deadline=deadline)
        if "ids" not in out or "distances" not in out:
            raise RemoteError(
                f"malformed shard answer from {rec['node_id']}: "
                f"{sorted(out)}", [(rec["node_id"], "malformed")])
        return out

    # ---- public API ------------------------------------------------------
    def search(self, queries, k: int, *,
               mode: Optional[str] = None,
               deadline: Optional[Deadline] = None,
               require_full: bool = False) -> Dict[str, Any]:
        """Scatter-gather one query batch across the cluster. Returns
        ``{"distances": [B, k], "ids": [B, k], "partial": bool,
        "shards_total": n, "shards_answered": m, "index_version": v}``
        (numpy arrays). ``partial`` means at least one shard had no
        surviving owner — the top-k covers only the answering slice."""
        q = np.asarray(queries, np.float32)  # host-sync-ok: client-side host data, HTTP egress
        single = q.ndim == 1
        if single:
            q = q[None, :]
        owners, universe = self.shard_map()
        if not universe:
            raise RemoteError("no retrieval nodes gossiping shards in "
                              f"the registry at {self.registry.dir!r}",
                              [])
        payload: Dict[str, Any] = {"queries": q.tolist(), "k": int(k)}
        if mode:
            payload["mode"] = mode
        if deadline is not None:
            payload["deadline_ms"] = max(
                deadline.remaining_s(), 0.0) * 1e3
        answered: Dict[int, None] = {}
        answers: List[Tuple[np.ndarray, np.ndarray]] = []
        version = None
        missing = list(universe)
        # round 0: primary owners; round 1: retry the missing shards on
        # any surviving replica not yet tried for them
        tried: Dict[int, set] = {s: set() for s in universe}
        for round_no in range(2):
            if not missing:
                break
            if deadline is not None and deadline.expired:
                break
            groups = self._group(missing, owners, tried)
            if not groups:
                break
            self._g_fanout.set(float(len(groups)))  # host-sync-ok: python int count to gauge
            futs = {
                self._pool.submit(self._leg, rec, shards, payload,
                                  deadline): (rec, shards)
                for rec, shards in groups}
            for f in futs:
                rec, shards = futs[f]
                try:
                    out = f.result()
                except Exception:
                    self._c_shard_req.inc(1.0, outcome="error")
                    continue
                self._c_shard_req.inc(1.0, outcome="ok")
                answers.append((
                    np.asarray(out["distances"], np.float32),  # host-sync-ok: decoding a peer's JSON shard answer, already host data
                    np.asarray(out["ids"], np.int32)))  # host-sync-ok: decoding a peer's JSON shard answer, already host data
                version = out.get("index_version", version)
                for s in shards:
                    answered[s] = None
            missing = [s for s in universe if s not in answered]
        partial = bool(missing)
        if partial:
            if require_full:
                raise PartialResultError(
                    f"shards {missing} unanswered (owners down/"
                    f"breaker-open) out of {len(universe)}")
            self._c_partial.inc(float(q.shape[0]))  # host-sync-ok: python int batch size to counter
        if not answers:
            raise RemoteError(
                f"every shard owner failed for shards {missing}", [])
        t0 = time.perf_counter()
        kk = answers[0][0].shape[1]
        d = np.stack([a[0] for a in answers])
        i = np.stack([a[1] for a in answers])
        md, mi = merge_topk(d, i, min(k, kk))
        dt = time.perf_counter() - t0
        self.merge_ring.record(dt)
        self._g_merge.set(dt)
        out = {"distances": md[0] if single else md,
               "ids": mi[0] if single else mi,
               "partial": partial,
               "shards_total": len(universe),
               "shards_answered": len(answered),
               "index_version": version}
        return out

    def _group(self, shards: List[int],
               owners: Dict[int, List[Dict[str, Any]]],
               tried: Dict[int, set]
               ) -> List[Tuple[Dict[str, Any], List[int]]]:
        """Assign each missing shard to one untried owner, balancing
        by assigned-so-far, then coalesce per node (one HTTP round
        trip per owning node, not per shard)."""
        load: Dict[str, int] = {}
        per_node: Dict[str, Tuple[Dict[str, Any], List[int]]] = {}
        for s in shards:
            cands = [r for r in owners.get(s, ())
                     if r["node_id"] not in tried[s]]
            if not cands:
                continue
            rec = min(cands,
                      key=lambda r: (load.get(r["node_id"], 0),
                                     r["node_id"]))
            nid = rec["node_id"]
            tried[s].add(nid)
            load[nid] = load.get(nid, 0) + 1
            per_node.setdefault(nid, (rec, []))[1].append(s)
        return list(per_node.values())

    def shutdown(self):
        self._pool.shutdown(wait=False)
        if self._owns_rd:
            self._rd.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
