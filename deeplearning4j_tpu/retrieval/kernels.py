"""Fused distance + top-k kernels.

One jitted function is one whole query batch against one corpus shard:
the N×B distance matrix is computed in the expanded-quadratic matmul
form (``d² = |q|² - 2·q·cᵀ + |c|²`` — the same single-matmul shape
``clustering/kmeans._assign`` uses, so the MXU does the O(B·N·D) work)
and ``lax.top_k`` runs in-graph on the negated distances, so the only
device→host transfer per (query, shard) is k indices + k distances.

Precision arms:

- **f32** — exact squared-L2 over the float corpus.
- **int8** — the corpus shard is per-row symmetric int8
  (``ops/quantize.quantize_rows``, 4× density); the query batch is
  quantized per-row *in-graph* (a [B] reduction fused into the kernel —
  unlike serving activations there is no offline calibration set for
  unseen queries, and the reduction never leaves the device), the dot
  runs int8×int8→int32 on the integer MAC path and dequantizes with
  ``q_scale[b]·row_scale[n]`` fused into the distance.

IVF arms route through k-means centroids: top-``nprobe`` clusters per
query, then a ``lax.scan`` over the probe axis with a running-top-k
carry — per step one [B, M, D] cluster gather + distance + a top-k
merge of (carry k + cluster M) candidates. Fixed (B, nprobe, M) shapes
keep the executable count finite; padded rows carry ``+inf`` distance
(and id -1) so they can never enter the top-k.

Every function is shape-polymorphic only in the static ``k`` (and
``nprobe``) arguments — the engine's warmup sweep enumerates the
(bucket, k, precision, mode) lattice once and the watchdog holds the
zero-live-compile contract afterwards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.quantize import Q_MAX

# distances for padded / masked-out candidates; jnp.inf survives the
# top-k negation (-inf sorts last) and compares correctly against any
# real squared distance
_PAD_D2 = jnp.inf


def _quantize_queries(q):
    """Per-row symmetric int8 quantization of the query batch, fused
    in-graph: scale[b] = absmax(q[b])/127 (dead rows scale 1). Returns
    ``(q_int8 [B, D], scales f32 [B, 1])``."""
    amax = jnp.max(jnp.abs(q), axis=1, keepdims=True)        # [B, 1]
    scale = jnp.where(amax > 0, amax, jnp.float32(Q_MAX)) \
        / jnp.float32(Q_MAX)
    qq = jnp.clip(jnp.round(q / scale), -Q_MAX, Q_MAX).astype(jnp.int8)
    return qq, scale


@functools.partial(jax.jit, static_argnames=("k",))
def brute_topk_f32(q, corpus, c2, ids, k):
    """Exact fused brute force: ``q`` [B, D] f32 against one f32 shard
    [R, D] with precomputed row norms ``c2`` [R] (``+inf`` on padding
    rows) and global ids ``ids`` [R] int32 (-1 on padding). Returns
    (distances [B, k] f32 ascending, global ids [B, k] int32)."""
    q2 = jnp.sum(q * q, axis=1, keepdims=True)               # [B, 1]
    d2 = q2 - 2.0 * (q @ corpus.T) + c2[None, :]             # [B, R]
    neg, pos = lax.top_k(-d2, k)
    return -neg, ids[pos]


@functools.partial(jax.jit, static_argnames=("k",))
def brute_topk_int8(q, corpus_q, row_scales, c2, ids, k):
    """Int8 fused brute force: int8×int8→int32 dot on the integer MAC
    path, dequant-rescale fused into the distance. ``c2`` is the row
    norm of the DEQUANTIZED shard (computed at index build) so the
    distance algebra is self-consistent with the quantized cross term.
    """
    q2 = jnp.sum(q * q, axis=1, keepdims=True)               # [B, 1]
    qq, q_scale = _quantize_queries(q)
    dots = lax.dot_general(
        qq, corpus_q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                    # [B, R]
    dots = dots.astype(jnp.float32) * (q_scale * row_scales[None, :])
    d2 = q2 - 2.0 * dots + c2[None, :]
    neg, pos = lax.top_k(-d2, k)
    return -neg, ids[pos]


def _ivf_scan(q, q2, probes, body_d2, c_c2, c_ids, k, nprobe):
    """Shared IVF probe loop: scan the top-``nprobe`` clusters with a
    running top-k carry. ``body_d2(cluster_rows_idx)`` returns the
    [B, M] distance block for the probed cluster of each query."""
    b = q.shape[0]
    init = (jnp.full((b, k), _PAD_D2, jnp.float32),
            jnp.full((b, k), -1, jnp.int32))

    def step(carry, p):
        best_d, best_i = carry
        cp = probes[:, p]                                    # [B]
        d2 = body_d2(cp) + c_c2[cp]                          # [B, M]
        cat_d = jnp.concatenate([best_d, d2], axis=1)        # [B, k+M]
        cat_i = jnp.concatenate([best_i, c_ids[cp]], axis=1)
        neg, pos = lax.top_k(-cat_d, k)
        return (-neg, jnp.take_along_axis(cat_i, pos, axis=1)), None

    (d, i), _ = lax.scan(step, init, jnp.arange(nprobe))
    return d, i


@functools.partial(jax.jit, static_argnames=("k", "nprobe"))
def ivf_topk_f32(q, centroids, clustered, c_c2, c_ids, k, nprobe):
    """IVF-routed f32 search: ``centroids`` [K, D], ``clustered``
    [K, M, D] (cluster-major padded corpus), ``c_c2`` [K, M] row norms
    (``+inf`` padding), ``c_ids`` [K, M] global ids (-1 padding).
    Probes the ``nprobe`` nearest clusters per query."""
    q2 = jnp.sum(q * q, axis=1, keepdims=True)               # [B, 1]
    cent2 = jnp.sum(centroids * centroids, axis=1)[None, :]
    cd2 = q2 - 2.0 * (q @ centroids.T) + cent2               # [B, K]
    _, probes = lax.top_k(-cd2, nprobe)                      # [B, P]

    def body(cp):
        sub = clustered[cp]                                  # [B, M, D]
        return q2 - 2.0 * jnp.einsum("bd,bmd->bm", q, sub)

    return _ivf_scan(q, q2, probes, body, c_c2, c_ids, k, nprobe)


@functools.partial(jax.jit, static_argnames=("k", "nprobe"))
def ivf_topk_int8(q, centroids, clustered_q, c_scales, c_c2, c_ids,
                  k, nprobe):
    """IVF-routed int8 search: centroid routing stays f32 (K·D is tiny
    next to the corpus), the per-cluster distance block runs the int8
    MAC path with fused dequant like :func:`brute_topk_int8`."""
    q2 = jnp.sum(q * q, axis=1, keepdims=True)
    cent2 = jnp.sum(centroids * centroids, axis=1)[None, :]
    cd2 = q2 - 2.0 * (q @ centroids.T) + cent2
    _, probes = lax.top_k(-cd2, nprobe)
    qq, q_scale = _quantize_queries(q)

    def body(cp):
        sub = clustered_q[cp]                                # [B, M, D]
        dots = jnp.einsum("bd,bmd->bm", qq, sub,
                          preferred_element_type=jnp.int32)
        return q2 - 2.0 * (dots.astype(jnp.float32)
                           * (q_scale * c_scales[cp]))

    return _ivf_scan(q, q2, probes, body, c_c2, c_ids, k, nprobe)
