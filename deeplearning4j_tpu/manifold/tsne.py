"""t-SNE on device.

Analog of deeplearning4j-manifold (SURVEY §2.9): Tsne.java (exact) and
BarnesHutTsne.java (SpTree-approximated). TPU-first inversion: the exact
O(N²) gradient is two dense matmuls + elementwise work — exactly what the
MXU does at full tilt — so for the N ≤ ~50k regime DL4J targets, the
exact device kernel outruns a host-side Barnes-Hut walk. ``BarnesHutTsne``
keeps the reference's class name/knobs (theta, perplexity, momentum
schedule, early exaggeration) and delegates: theta == 0 → exact device
path; theta > 0 → SpTree approximation on host (clustering/sptree.py)
for memory-bound N.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.sptree import SpTree


def _hbeta(d2_row: np.ndarray, beta: float):
    p = np.exp(-d2_row * beta)
    sum_p = max(p.sum(), 1e-12)
    h = np.log(sum_p) + beta * float(d2_row @ p) / sum_p
    return h, p / sum_p


def _binary_search_perplexity(d2: np.ndarray, perplexity: float,
                              tol: float = 1e-5) -> np.ndarray:
    """Per-row beta search so each conditional P has the target entropy
    (reference: Tsne.java computeGaussianPerplexity)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    p = np.zeros_like(d2)
    for i in range(n):
        row = np.delete(d2[i], i)
        beta, lo, hi = 1.0, -np.inf, np.inf
        for _ in range(50):
            h, pr = _hbeta(row, beta)
            if abs(h - target) < tol:
                break
            if h > target:
                lo = beta
                beta = beta * 2 if hi == np.inf else (beta + hi) / 2
            else:
                hi = beta
                beta = beta / 2 if lo == -np.inf else (beta + lo) / 2
        p[i] = np.insert(pr, i, 0.0)
    return p


@functools.partial(jax.jit, donate_argnums=(1, 2, 3))
def _tsne_step(P, y, vel, gains, momentum, lr):
    """One exact gradient-descent step with gains + momentum (reference:
    Tsne.java gradient/step math). All O(N²) terms are device matmuls."""
    y2 = jnp.sum(y * y, axis=1)
    d2 = y2[:, None] - 2.0 * (y @ y.T) + y2[None, :]
    num = 1.0 / (1.0 + d2)
    num = num * (1.0 - jnp.eye(y.shape[0], dtype=y.dtype))
    Q = num / jnp.maximum(num.sum(), 1e-12)
    PQ = (P - jnp.maximum(Q, 1e-12)) * num
    grad = 4.0 * ((jnp.diag(PQ.sum(1)) - PQ) @ y)
    gains = jnp.where(jnp.sign(grad) != jnp.sign(vel),
                      gains + 0.2, gains * 0.8)
    gains = jnp.maximum(gains, 0.01)
    vel = momentum * vel - lr * gains * grad
    y = y + vel
    y = y - y.mean(0)
    kl = jnp.sum(jnp.where(P > 0,
                           P * jnp.log(jnp.maximum(P, 1e-12)
                                       / jnp.maximum(Q, 1e-12)), 0.0))
    return y, vel, gains, kl


class Tsne:
    """Exact t-SNE (reference: plot/Tsne.java builder knobs)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 early_exaggeration: float = 12.0,
                 exaggeration_iters: int = 100,
                 initial_momentum: float = 0.5,
                 final_momentum: float = 0.8,
                 momentum_switch: int = 250, seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.early_exaggeration = early_exaggeration
        self.exaggeration_iters = exaggeration_iters
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.seed = seed
        self.kl_divergence_: Optional[float] = None

    def _p_matrix(self, x: np.ndarray) -> np.ndarray:
        x2 = np.sum(x * x, axis=1)
        d2 = np.maximum(x2[:, None] - 2.0 * (x @ x.T) + x2[None, :], 0.0)
        p = _binary_search_perplexity(d2, self.perplexity)
        p = (p + p.T) / (2.0 * p.shape[0])
        return np.maximum(p, 1e-12)

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        P = jnp.asarray(self._p_matrix(x), jnp.float32)
        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.normal(scale=1e-4,
                                   size=(n, self.n_components))
                        .astype(np.float32))
        vel = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        kl = jnp.asarray(jnp.nan)
        for it in range(self.n_iter):
            ex = (self.early_exaggeration
                  if it < self.exaggeration_iters else 1.0)
            mom = (self.initial_momentum
                   if it < self.momentum_switch else self.final_momentum)
            y, vel, gains, kl = _tsne_step(
                P * ex if ex != 1.0 else P, y, vel, gains,
                jnp.float32(mom), jnp.float32(self.learning_rate))
        self.kl_divergence_ = float(kl)
        return np.asarray(y)


class BarnesHutTsne(Tsne):
    """reference: plot/BarnesHutTsne.java — theta-approximated t-SNE.
    theta == 0 runs the exact device kernel; theta > 0 runs the SpTree
    approximation on host for memory-bound N."""

    def __init__(self, theta: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.theta = theta

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        if self.theta <= 0.0:
            return super().fit_transform(x)
        return self._fit_bh(np.asarray(x, np.float64))

    def _fit_bh(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        P = self._p_matrix(x)          # dense input affinities
        rng = np.random.default_rng(self.seed)
        y = rng.normal(scale=1e-4, size=(n, self.n_components))
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        for it in range(self.n_iter):
            ex = (self.early_exaggeration
                  if it < self.exaggeration_iters else 1.0)
            mom = (self.initial_momentum
                   if it < self.momentum_switch else self.final_momentum)
            tree = SpTree(y)
            neg = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                f, q = tree.compute_non_edge_forces(i, self.theta)
                neg[i] = f
                sum_q += q
            sum_q = max(sum_q, 1e-12)
            # attractive forces from P (dense; sparse in the reference).
            # O(N^2) memory: pairwise distances via the norm expansion and
            # pos_i = sum_j w_ij (y_i - y_j) = rowsum(w)*y_i - (w @ y)_j —
            # never materializing the (N, N, D) difference tensor.
            sq = np.sum(y * y, axis=1)
            dist2 = np.maximum(sq[:, None] + sq[None, :] - 2.0 * (y @ y.T),
                               0.0)
            w = (P * ex) / (1.0 + dist2)
            pos = w.sum(axis=1)[:, None] * y - w @ y
            # same 4x scale as the exact-path gradient (_tsne_step)
            grad = 4.0 * (pos - neg / sum_q)
            gains = np.where(np.sign(grad) != np.sign(vel),
                             gains + 0.2, gains * 0.8)
            gains = np.maximum(gains, 0.01)
            vel = mom * vel - self.learning_rate * gains * grad
            y = y + vel
            y = y - y.mean(0)
        self.kl_divergence_ = None
        return y
