"""Manifold learning (t-SNE) — analog of deeplearning4j-manifold."""

from deeplearning4j_tpu.manifold.tsne import BarnesHutTsne, Tsne

__all__ = ["Tsne", "BarnesHutTsne"]
