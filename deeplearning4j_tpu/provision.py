"""Cluster provisioning — TPU-VM / GKE job generation.

Analog of the reference's ``deeplearning4j-aws`` module (SURVEY §2.11:
``ec2/provision/ClusterSetup.java``, ``emr/SparkEMRClient.java``, ``s3/``):
where the reference provisions EC2/EMR clusters for Spark training, the
TPU-native equivalent targets Cloud TPU VMs and GKE. This module
*generates* the provisioning artifacts (gcloud command scripts, GKE
JobSet-style manifests, multi-host launch wrappers around
``jax.distributed.initialize``) rather than calling cloud APIs directly,
so it works air-gapped and the artifacts are auditable before running —
the same role ClusterSetup's command builders play.
"""

from __future__ import annotations

import json
import shlex
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TpuClusterSpec:
    """What to provision (reference: ClusterSetup's CLI params)."""

    name: str = "dl4j-tpu-job"
    accelerator_type: str = "v5litepod-8"   # e.g. v4-32, v5p-128
    zone: str = "us-central2-b"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: Optional[str] = None
    preemptible: bool = False
    num_slices: int = 1                      # >1 → multislice over DCN
    setup_commands: List[str] = field(default_factory=lambda: [
        "pip install -U jax[tpu] -f "
        "https://storage.googleapis.com/jax-releases/libtpu_releases.html",
    ])
    env: Dict[str, str] = field(default_factory=dict)


def gcloud_create_script(spec: TpuClusterSpec) -> str:
    """gcloud commands that create the TPU VM(s) (ClusterSetup analog)."""
    lines = ["#!/usr/bin/env bash", "set -euo pipefail", ""]
    proj = f" --project={shlex.quote(spec.project)}" if spec.project else ""
    for s in range(spec.num_slices):
        name = spec.name if spec.num_slices == 1 else f"{spec.name}-s{s}"
        cmd = (f"gcloud compute tpus tpu-vm create {shlex.quote(name)}"
               f" --zone={shlex.quote(spec.zone)}"
               f" --accelerator-type={shlex.quote(spec.accelerator_type)}"
               f" --version={shlex.quote(spec.runtime_version)}{proj}")
        if spec.preemptible:
            cmd += " --preemptible"
        lines.append(cmd)
    lines.append("")
    for s in range(spec.num_slices):
        name = spec.name if spec.num_slices == 1 else f"{spec.name}-s{s}"
        for setup in spec.setup_commands:
            lines.append(
                f"gcloud compute tpus tpu-vm ssh {shlex.quote(name)}"
                f" --zone={shlex.quote(spec.zone)}{proj} --worker=all"
                f" --command={shlex.quote(setup)}")
    return "\n".join(lines) + "\n"


def gcloud_delete_script(spec: TpuClusterSpec) -> str:
    proj = f" --project={shlex.quote(spec.project)}" if spec.project else ""
    lines = ["#!/usr/bin/env bash", "set -euo pipefail", ""]
    for s in range(spec.num_slices):
        name = spec.name if spec.num_slices == 1 else f"{spec.name}-s{s}"
        lines.append(
            f"gcloud compute tpus tpu-vm delete {shlex.quote(name)}"
            f" --zone={shlex.quote(spec.zone)}{proj} --quiet")
    return "\n".join(lines) + "\n"


def launch_script(spec: TpuClusterSpec, train_command: str) -> str:
    """Run a training command on every worker of every slice. The command
    sees standard TPU env (the runtime wires coordinator discovery;
    ``jax.distributed.initialize()`` picks it up with no args)."""
    proj = f" --project={shlex.quote(spec.project)}" if spec.project else ""
    env_prefix = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in spec.env.items())
    full = (env_prefix + " " if env_prefix else "") + train_command
    lines = ["#!/usr/bin/env bash", "set -euo pipefail", ""]
    for s in range(spec.num_slices):
        name = spec.name if spec.num_slices == 1 else f"{spec.name}-s{s}"
        lines.append(
            f"gcloud compute tpus tpu-vm ssh {shlex.quote(name)}"
            f" --zone={shlex.quote(spec.zone)}{proj} --worker=all"
            f" --command={shlex.quote(full)} &")
    lines.append("wait")
    return "\n".join(lines) + "\n"


def gke_jobset_manifest(spec: TpuClusterSpec, image: str,
                        train_command: List[str]) -> str:
    """Kubernetes JobSet-style manifest for TPU slices on GKE (the EMR
    analog: managed-cluster submission instead of raw VMs)."""
    chips_per_host = 4
    topo = spec.accelerator_type
    manifest = {
        "apiVersion": "jobset.x-k8s.io/v1alpha2",
        "kind": "JobSet",
        "metadata": {"name": spec.name},
        "spec": {
            "replicatedJobs": [{
                "name": "workers",
                "replicas": spec.num_slices,
                "template": {"spec": {
                    "backoffLimit": 0,
                    "completions": 1,
                    "parallelism": 1,
                    "template": {"spec": {
                        "nodeSelector": {
                            "cloud.google.com/gke-tpu-accelerator": topo,
                        },
                        "containers": [{
                            "name": "train",
                            "image": image,
                            "command": train_command,
                            "env": [{"name": k, "value": v}
                                    for k, v in spec.env.items()],
                            "resources": {"limits": {
                                "google.com/tpu": chips_per_host}},
                        }],
                        "restartPolicy": "Never",
                    }},
                }},
            }],
        },
    }
    return json.dumps(manifest, indent=2)


def write_provisioning_bundle(spec: TpuClusterSpec, out_dir: str,
                              train_command: str = "python train.py"
                              ) -> List[str]:
    """Emit create/launch/delete scripts + GKE manifest into out_dir."""
    import os
    os.makedirs(out_dir, exist_ok=True)
    files = {
        "create_cluster.sh": gcloud_create_script(spec),
        "launch.sh": launch_script(spec, train_command),
        "delete_cluster.sh": gcloud_delete_script(spec),
        "gke_jobset.json": gke_jobset_manifest(
            spec, "python:3.12", train_command.split()),
    }
    written = []
    for name, content in files.items():
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            f.write(content)
        if name.endswith(".sh"):
            os.chmod(path, 0o755)
        written.append(path)
    return written
