"""Tokenizer pipeline.

Analog of the reference's text/tokenization/{tokenizer,tokenizerfactory}
(deeplearning4j-nlp, SURVEY §2.7): a TokenizerFactory produces a Tokenizer
per sentence; an optional TokenPreProcess normalises each token.
"""

from __future__ import annotations

import re
from typing import Iterator, List, Optional


class TokenPreProcess:
    """Token normaliser SPI (reference: tokenization/tokenizer/
    TokenPreProcess.java)."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference:
    tokenizer/preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer dropping common English endings (reference:
    tokenizer/preprocessor/EndingPreProcessor.java)."""

    def pre_process(self, token: str) -> str:
        for ending in ("sses", "ies", "ing", "ed", "s"):
            if token.endswith(ending) and len(token) > len(ending) + 2:
                if ending == "sses":
                    return token[:-2]
                if ending == "ies":
                    return token[:-3] + "y"
                return token[: -len(ending)]
        return token


class Tokenizer:
    """One sentence's token stream (reference: tokenizer/Tokenizer.java)."""

    def __init__(self, tokens: List[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor
        self._idx = 0

    def has_more_tokens(self) -> bool:
        return self._idx < len(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def next_token(self) -> str:
        tok = self._tokens[self._idx]
        self._idx += 1
        return self._pre.pre_process(tok) if self._pre else tok

    def get_tokens(self) -> List[str]:
        out = []
        while self.has_more_tokens():
            tok = self.next_token()
            if tok:
                out.append(tok)
        return out

    def __iter__(self) -> Iterator[str]:
        return iter(self.get_tokens())


class TokenizerFactory:
    """Factory SPI (reference: tokenizerfactory/TokenizerFactory.java)."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre
        return self

    def create(self, sentence: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference: tokenizerfactory/
    DefaultTokenizerFactory.java wraps DefaultTokenizer, a
    StringTokenizer on whitespace)."""

    def create(self, sentence: str) -> Tokenizer:
        return Tokenizer(sentence.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over a base tokenizer (reference: tokenizerfactory/
    NGramTokenizerFactory.java)."""

    def __init__(self, base: Optional[TokenizerFactory] = None,
                 min_n: int = 1, max_n: int = 2):
        super().__init__()
        self._base = base or DefaultTokenizerFactory()
        self.min_n = min_n
        self.max_n = max_n

    def create(self, sentence: str) -> Tokenizer:
        words = self._base.create(sentence).get_tokens()
        grams: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(words) - n + 1):
                grams.append(" ".join(words[i:i + n]))
        return Tokenizer(grams, self._pre)


# reference: deeplearning4j-nlp/src/main/resources/stopwords (vendored list);
# a compact English subset serves the same role for vocab filtering.
DEFAULT_STOP_WORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split())
