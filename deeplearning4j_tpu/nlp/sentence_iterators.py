"""Sentence / document iterators.

Analog of the reference's text/sentenceiterator/ and text/documentiterator/
(SURVEY §2.7): streams of sentences (strings) or labelled documents feeding
vocab construction and training. Python iterables replace the reference's
hasNext/nextSentence protocol; ``reset()`` restarts the stream.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, Iterator, List, Optional


class SentenceIterator:
    """reference: sentenceiterator/SentenceIterator.java"""

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSentenceIterator(SentenceIterator):
    """In-memory list of sentences (reference: sentenceiterator/
    CollectionSentenceIterator.java)."""

    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self._sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference: sentenceiterator/
    BasicLineIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[str]:
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, line by line (reference:
    sentenceiterator/FileSentenceIterator.java)."""

    def __init__(self, root: str):
        self.root = root

    def __iter__(self) -> Iterator[str]:
        if os.path.isfile(self.root):
            yield from BasicLineIterator(self.root)
            return
        for dirpath, _dirs, files in os.walk(self.root):
            for name in sorted(files):
                yield from BasicLineIterator(os.path.join(dirpath, name))


@dataclasses.dataclass
class LabelledDocument:
    """reference: documentiterator/LabelledDocument.java"""
    content: str
    labels: List[str]

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelAwareIterator:
    """reference: documentiterator/LabelAwareIterator.java"""

    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionLabelledDocumentIterator(LabelAwareIterator):
    def __init__(self, docs: Iterable[LabelledDocument]):
        self._docs = list(docs)

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self._docs)


class SentenceLabelledIterator(LabelAwareIterator):
    """Wrap a SentenceIterator, auto-assigning DOC_<n> labels (reference:
    ParagraphVectors falls back to synthetic labels via
    documentiterator/DocumentIterator adapters)."""

    def __init__(self, sentences: Iterable[str], prefix: str = "DOC_"):
        self._sentences = list(sentences)
        self._prefix = prefix

    def __iter__(self) -> Iterator[LabelledDocument]:
        for i, s in enumerate(self._sentences):
            yield LabelledDocument(s, [f"{self._prefix}{i}"])
