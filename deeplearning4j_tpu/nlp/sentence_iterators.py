"""Sentence / document iterators.

Analog of the reference's text/sentenceiterator/ and text/documentiterator/
(SURVEY §2.7): streams of sentences (strings) or labelled documents feeding
vocab construction and training. Python iterables replace the reference's
hasNext/nextSentence protocol; ``reset()`` restarts the stream.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, Iterator, List, Optional


class SentenceIterator:
    """reference: sentenceiterator/SentenceIterator.java"""

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSentenceIterator(SentenceIterator):
    """In-memory list of sentences (reference: sentenceiterator/
    CollectionSentenceIterator.java)."""

    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self._sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference: sentenceiterator/
    BasicLineIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[str]:
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, line by line (reference:
    sentenceiterator/FileSentenceIterator.java)."""

    def __init__(self, root: str):
        self.root = root

    def __iter__(self) -> Iterator[str]:
        if os.path.isfile(self.root):
            yield from BasicLineIterator(self.root)
            return
        for dirpath, _dirs, files in os.walk(self.root):
            for name in sorted(files):
                yield from BasicLineIterator(os.path.join(dirpath, name))


@dataclasses.dataclass
class LabelledDocument:
    """reference: documentiterator/LabelledDocument.java"""
    content: str
    labels: List[str]

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelAwareIterator:
    """reference: documentiterator/LabelAwareIterator.java"""

    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionLabelledDocumentIterator(LabelAwareIterator):
    def __init__(self, docs: Iterable[LabelledDocument]):
        self._docs = list(docs)

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self._docs)


class SentenceLabelledIterator(LabelAwareIterator):
    """Wrap a SentenceIterator, auto-assigning DOC_<n> labels (reference:
    ParagraphVectors falls back to synthetic labels via
    documentiterator/DocumentIterator adapters)."""

    def __init__(self, sentences: Iterable[str], prefix: str = "DOC_"):
        self._sentences = list(sentences)
        self._prefix = prefix

    def __iter__(self) -> Iterator[LabelledDocument]:
        for i, s in enumerate(self._sentences):
            yield LabelledDocument(s, [f"{self._prefix}{i}"])


#: end-of-stream marker frame for broker-fed sentence topics
SENTENCE_EOS = b""


def publish_sentences(transport, sentences: Iterable[str],
                      topic: str = "sentences", *,
                      eos: bool = True) -> int:
    """Feed a sentence stream into a broker topic, one UTF-8 frame per
    sentence; ``eos=True`` appends the empty end-of-stream frame so a
    ``StreamingSentenceIterator`` terminates instead of idling out.
    Returns the number of sentences published."""
    n = 0
    for s in sentences:
        s = s.strip()
        if not s:
            continue
        transport.publish(topic, s.encode("utf-8"))
        n += 1
    if eos:
        transport.publish(topic, SENTENCE_EOS)
    return n


class StreamingSentenceIterator(SentenceIterator):
    """Broker-backed unbounded sentence stream (streaming/broker.py):
    one UTF-8 frame per sentence, over any Transport — InProcess for
    tests, TcpTransport across processes (the DataVec-streaming shape,
    SURVEY §2.11). Iteration ends on the empty end-of-stream frame, a
    ``max_sentences`` cap, a set ``stop_event``, or ``idle_timeout_s``
    with nothing arriving.

    The stream is unbounded and consume-once: ``reset()`` is a no-op,
    so this iterator feeds windowed consumers (``Word2Vec.fit_stream``)
    or a ``CorpusShardWriter`` spool — not multi-pass ``fit``.

    A dead broker is NOT a quiet topic: a transport whose retries are
    exhausted (``ConnectionError``/``OSError`` out of ``poll``)
    terminates the stream immediately with ``termination_reason =
    "transport_dead"`` (and the error text in ``transport_error``)
    instead of idling silently until ``idle_timeout_s`` — before this,
    the two cases were indistinguishable to the consumer.
    ``termination_reason`` after iteration is one of ``"eos"`` |
    ``"max_sentences"`` | ``"stopped"`` | ``"idle_timeout"`` |
    ``"transport_dead"``."""

    def __init__(self, transport, topic: str = "sentences", *,
                 poll_timeout_s: float = 0.2,
                 idle_timeout_s: Optional[float] = None,
                 max_sentences: Optional[int] = None,
                 stop_event=None):
        self.transport = transport
        self.topic = topic
        self.poll_timeout_s = float(poll_timeout_s)
        self.idle_timeout_s = idle_timeout_s
        self.max_sentences = max_sentences
        self.stop_event = stop_event
        self.consumed = 0
        self.termination_reason: Optional[str] = None
        self.transport_error: Optional[str] = None

    def __iter__(self) -> Iterator[str]:
        import time
        self.termination_reason = None
        self.transport_error = None
        idle = 0.0
        while True:
            if self.stop_event is not None and self.stop_event.is_set():
                self.termination_reason = "stopped"
                return
            if (self.max_sentences is not None
                    and self.consumed >= self.max_sentences):
                self.termination_reason = "max_sentences"
                return
            t0 = time.monotonic()
            try:
                payload = self.transport.poll(self.topic,
                                              self.poll_timeout_s)
            except (ConnectionError, OSError) as e:
                self.termination_reason = "transport_dead"
                self.transport_error = str(e)
                return
            if payload is None:
                idle += time.monotonic() - t0
                if (self.idle_timeout_s is not None
                        and idle >= self.idle_timeout_s):
                    self.termination_reason = "idle_timeout"
                    return
                continue
            idle = 0.0
            if payload == SENTENCE_EOS:
                self.termination_reason = "eos"
                return
            s = payload.decode("utf-8", errors="replace").strip()
            if s:
                self.consumed += 1
                yield s
