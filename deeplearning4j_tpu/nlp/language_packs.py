"""Language-pack tokenizers: Chinese, Japanese, Korean, and a UIMA-style
annotator pipeline.

Analogs of the reference's per-language NLP modules (SURVEY §2.7):
``deeplearning4j-nlp-chinese`` (vendored Ansj segmenter),
``-japanese`` (Kuromoji), ``-korean`` (KoreanAnalyzer twitter-text), and
``-uima`` (UIMA annotator pipeline). Those modules vendor large
dictionary-driven analyzers; here each language gets a self-contained
statistical/rule segmenter with the same ``TokenizerFactory`` contract, so
``Word2Vec``/``SequenceVectors`` pipelines work identically across
languages. A user-supplied dictionary (one word per line, cached under
``DL4J_TPU_DATA_DIR``) upgrades segmentation quality without code changes
— the same posture as the dataset fetchers' cache contract.
"""

from __future__ import annotations

import os
import re
from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.nlp.tokenization import (
    Tokenizer,
    TokenizerFactory,
)

_DATA_DIR = os.environ.get("DL4J_TPU_DATA_DIR",
                           os.path.expanduser("~/.deeplearning4j_tpu/data"))


def _load_dict(name: str) -> Optional[set]:
    path = os.path.join(_DATA_DIR, "dicts", name)
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            return {line.strip() for line in f if line.strip()}
    return None


# ---------------------------------------------------------------------------
# Chinese — forward maximum matching over a dictionary, char fallback
# (reference: deeplearning4j-nlp-chinese vendored Ansj)
# ---------------------------------------------------------------------------

_CJK = r"一-鿿㐀-䶿"
# minimal seed vocabulary of common multi-char words so the segmenter is
# useful out of the box; a cached dict file extends it
_ZH_SEED = {
    "中国", "我们", "你们", "他们", "什么", "没有", "可以", "自己",
    "现在", "知道", "时候", "学习", "机器", "深度", "神经", "网络",
    "模型", "数据", "训练", "人工", "智能", "因为", "所以", "如果",
    "但是", "就是", "这个", "那个", "已经", "还是", "或者", "今天",
    "明天", "问题", "工作", "生活", "世界", "非常", "喜欢", "谢谢",
    # high-frequency everyday vocabulary
    "时间", "地方", "东西", "事情", "朋友", "老师", "学生", "学校",
    "公司", "国家", "城市", "北京", "上海", "电话", "电脑", "手机",
    "电视", "电影", "音乐", "新闻", "报纸", "文章", "历史", "文化",
    "经济", "政治", "社会", "科学", "技术", "发展", "研究", "教育",
    "医院", "医生", "健康", "身体", "运动", "比赛", "足球", "篮球",
    "飞机", "火车", "汽车", "地铁", "公共", "交通", "旅游", "旅行",
    "天气", "下雨", "下雪", "春天", "夏天", "秋天", "冬天", "早上",
    "中午", "晚上", "昨天", "后天", "星期", "月份", "去年", "明年",
    "大家", "别人", "先生", "小姐", "孩子", "父母", "家庭", "房子",
    "厨房", "商店", "超市", "市场", "银行", "钱包", "价格", "便宜",
    "开始", "结束", "继续", "停止", "出发", "到达", "回来", "离开",
    "认识", "了解", "理解", "记得", "忘记", "希望", "觉得", "认为",
    "应该", "必须", "需要", "帮助", "感谢", "对不起", "再见", "欢迎",
    "高兴", "快乐", "幸福", "难过", "生气", "害怕", "担心", "放心",
    "重要", "主要", "特别", "一般", "普通", "简单", "复杂", "容易",
    "困难", "方便", "安全", "危险", "干净", "漂亮", "好看", "有趣",
    "有名", "著名", "年轻", "聪明", "努力", "认真", "热情", "友好",
    "计算", "程序", "软件", "系统", "信息", "互联网", "网站", "网上",
    "语言", "文字", "汉语", "英语", "翻译", "词典", "意思", "内容",
    "方法", "办法", "结果", "原因", "影响", "变化", "情况", "环境",
    "大学", "中学", "小学", "处理", "分析", "设计", "管理", "服务",
    "自然", "动物", "植物", "森林", "河流", "海洋", "太阳", "月亮",
    "星星", "地球", "宇宙", "空气", "能源", "资源", "保护", "污染",
}


class ChineseTokenizerFactory(TokenizerFactory):
    """Forward-maximum-matching segmenter (reference:
    ChineseTokenizerFactory over Ansj). Longest dictionary word wins;
    unmatched CJK runs fall back to single characters; Latin/digit runs
    stay whole."""

    def __init__(self, dictionary: Optional[Iterable[str]] = None):
        super().__init__()
        words = set(_ZH_SEED)
        cached = _load_dict("chinese.txt")
        if cached:
            words |= cached
        if dictionary:
            words |= set(dictionary)
        self._dict = words
        self._max_len = max((len(w) for w in words), default=1)
        self._scanner = re.compile(
            rf"([{_CJK}]+)|([A-Za-z0-9]+)|(\S)")

    def _segment_cjk(self, run: str) -> List[str]:
        out = []
        i = 0
        n = len(run)
        while i < n:
            for l in range(min(self._max_len, n - i), 1, -1):
                if run[i:i + l] in self._dict:
                    out.append(run[i:i + l])
                    i += l
                    break
            else:
                out.append(run[i])
                i += 1
        return out

    def create(self, sentence: str) -> Tokenizer:
        tokens: List[str] = []
        for cjk, latin, other in self._scanner.findall(sentence):
            if cjk:
                tokens.extend(self._segment_cjk(cjk))
            elif latin:
                tokens.append(latin)
        return Tokenizer(tokens, self._pre)


# ---------------------------------------------------------------------------
# Japanese — script-transition segmentation (reference:
# deeplearning4j-nlp-japanese vendored Kuromoji)
# ---------------------------------------------------------------------------

_HIRA = r"぀-ゟ"
_KATA = r"゠-ヿㇰ-ㇿ"

# common kanji compounds so compound splitting works out of the box; a
# cached ``japanese.txt`` (Kuromoji/mecab-style word list) extends it
_JA_SEED = {
    "日本", "日本語", "東京", "会社", "仕事", "学校", "学生", "先生",
    "電話", "電車", "時間", "今日", "明日", "昨日", "今年", "去年",
    "毎日", "毎週", "午前", "午後", "世界", "国家", "社会", "経済",
    "政治", "歴史", "文化", "科学", "技術", "研究", "開発", "教育",
    "大学", "高校", "問題", "質問", "答え", "言葉", "文章", "意味",
    "情報", "新聞", "映画", "音楽", "写真", "料理", "食事", "朝食",
    "昼食", "夕食", "天気", "天気予報", "旅行", "観光", "案内",
    "家族", "友達", "子供", "両親", "兄弟", "姉妹", "結婚", "誕生日",
    "病院", "医者", "健康", "運動", "練習", "試験", "試合", "勉強",
    "機械", "学習", "機械学習", "人工", "知能", "人工知能", "深層",
    "自然", "言語", "処理", "自然言語", "計算", "計算機", "電脳",
    "銀行", "会議", "書類", "説明", "説明書", "住所", "名前", "番号",
}


class JapaneseTokenizerFactory(TokenizerFactory):
    """Segments on script transitions (kanji→hiragana starts a new
    content+inflection unit; katakana runs and Latin runs are single
    tokens), with hiragana particles split off. This is the classic
    "tiny segmenter" heuristic family; a cached ``japanese.txt``
    dictionary refines kanji compound splits via maximum matching."""

    _PARTICLES = {"は", "が", "を", "に", "へ", "と", "で", "の", "も",
                  "や", "から", "まで", "より", "ね", "よ", "か", "な"}

    def __init__(self, dictionary: Optional[Iterable[str]] = None):
        super().__init__()
        d = set(_JA_SEED)
        d |= set(_load_dict("japanese.txt") or ())
        if dictionary:
            d |= set(dictionary)
        self._dict = d
        self._max_len = max((len(w) for w in self._dict), default=1)
        self._scanner = re.compile(
            rf"([{_CJK}]+[{_HIRA}]*)|([{_KATA}]+)|([{_HIRA}]+)"
            rf"|([A-Za-z0-9]+)|(\S)")

    def _split_compound(self, run: str) -> List[str]:
        """Maximum-matching split of a kanji(+inflection) run against the
        dictionary; the whole run stays one token when nothing matches."""
        if not self._dict:
            return [run]
        out: List[str] = []
        buf = ""  # unmatched span stays one token, not per-char
        i, n = 0, len(run)
        while i < n:
            for l in range(min(self._max_len, n - i), 1, -1):
                if run[i:i + l] in self._dict:
                    if buf:
                        out.append(buf)
                        buf = ""
                    out.append(run[i:i + l])
                    i += l
                    break
            else:
                buf += run[i]
                i += 1
        if buf:
            out.append(buf)
        return out

    def _split_particles(self, run: str) -> List[str]:
        # peel trailing particles off a hiragana run, longest first
        out: List[str] = []
        while run:
            for l in (2, 1):
                if len(run) > l and run[-l:] in self._PARTICLES:
                    out.insert(0, run[-l:])
                    run = run[:-l]
                    break
            else:
                out.insert(0, run)
                break
        return out

    def create(self, sentence: str) -> Tokenizer:
        tokens: List[str] = []
        for kanji_mix, kata, hira, latin, _other in \
                self._scanner.findall(sentence):
            if kanji_mix:
                tokens.extend(self._split_compound(kanji_mix))
            elif kata:
                tokens.append(kata)
            elif hira:
                tokens.extend(self._split_particles(hira))
            elif latin:
                tokens.append(latin)
        return Tokenizer(tokens, self._pre)


# ---------------------------------------------------------------------------
# Korean — whitespace eojeol + particle (josa) stripping (reference:
# deeplearning4j-nlp-korean KoreanAnalyzer)
# ---------------------------------------------------------------------------


class KoreanTokenizerFactory(TokenizerFactory):
    """Splits on whitespace into eojeol, then strips common trailing
    particles (josa) so inflected forms share a stem token."""

    _JOSA = ("은", "는", "이", "가", "을", "를", "에", "의", "도",
             "으로", "로", "와", "과", "에서", "에게", "부터", "까지",
             "입니다", "합니다", "했다", "하다")

    def __init__(self, strip_josa: bool = True):
        super().__init__()
        self.strip_josa = strip_josa

    def _strip(self, word: str) -> str:
        if not self.strip_josa:
            return word
        for j in sorted(self._JOSA, key=len, reverse=True):
            if len(word) > len(j) and word.endswith(j):
                return word[:-len(j)]
        return word

    def create(self, sentence: str) -> Tokenizer:
        tokens = [self._strip(w) for w in re.findall(r"\S+", sentence)]
        return Tokenizer([t for t in tokens if t], self._pre)


# ---------------------------------------------------------------------------
# UIMA-style annotator pipeline (reference: deeplearning4j-nlp-uima —
# UimaTokenizerFactory / UimaSentenceIterator over an AnalysisEngine)
# ---------------------------------------------------------------------------


class Annotation:
    """A typed text span (the CAS annotation analog)."""

    __slots__ = ("type", "begin", "end", "text", "features")

    def __init__(self, type_: str, begin: int, end: int, text: str,
                 **features):
        self.type = type_
        self.begin = begin
        self.end = end
        self.text = text
        self.features = features

    def __repr__(self):
        return f"Annotation({self.type!r}, {self.begin}, {self.end}, " \
               f"{self.text!r})"


class CAS:
    """Common Analysis Structure: the document plus annotations by type."""

    def __init__(self, text: str):
        self.text = text
        self._by_type: dict = {}

    def add(self, ann: Annotation):
        self._by_type.setdefault(ann.type, []).append(ann)

    def select(self, type_: str) -> List[Annotation]:
        return list(self._by_type.get(type_, []))


class AnalysisEngine:
    """An annotator: process(cas) adds annotations."""

    def process(self, cas: CAS) -> None:
        raise NotImplementedError


class SentenceAnnotator(AnalysisEngine):
    _SPLIT = re.compile(r"[^.!?。！？]+[.!?。！？]?")

    def process(self, cas: CAS) -> None:
        for m in self._SPLIT.finditer(cas.text):
            s = m.group().strip()
            if s:
                cas.add(Annotation("sentence", m.start(), m.end(), s))


class TokenAnnotator(AnalysisEngine):
    def __init__(self, factory: Optional[TokenizerFactory] = None):
        from deeplearning4j_tpu.nlp.tokenization import (
            DefaultTokenizerFactory)
        self.factory = factory or DefaultTokenizerFactory()

    def process(self, cas: CAS) -> None:
        sentences = cas.select("sentence") or [
            Annotation("sentence", 0, len(cas.text), cas.text)]
        for sent in sentences:
            pos = sent.begin
            for tok in self.factory.create(sent.text).get_tokens():
                found = cas.text.find(tok, pos)
                b = found if found >= 0 else pos
                cas.add(Annotation("token", b, b + len(tok), tok))
                if found >= 0:
                    pos = found + len(tok)


class AnalysisPipeline:
    """Chains engines over a document (the AnalysisEngine aggregate)."""

    def __init__(self, engines: Sequence[AnalysisEngine]):
        self.engines = list(engines)

    def process(self, text: str) -> CAS:
        cas = CAS(text)
        for e in self.engines:
            e.process(cas)
        return cas


class UimaTokenizerFactory(TokenizerFactory):
    """Tokenizes via an annotator pipeline (reference:
    UimaTokenizerFactory) so custom annotators can rewrite the stream."""

    def __init__(self, pipeline: Optional[AnalysisPipeline] = None):
        super().__init__()
        self.pipeline = pipeline or AnalysisPipeline(
            [SentenceAnnotator(), TokenAnnotator()])

    def create(self, sentence: str) -> Tokenizer:
        cas = self.pipeline.process(sentence)
        return Tokenizer([a.text for a in cas.select("token")],
                         self._pre)


class UimaSentenceIterator:
    """Sentence iterator over documents via the pipeline (reference:
    UimaSentenceIterator)."""

    def __init__(self, documents: Sequence[str],
                 pipeline: Optional[AnalysisPipeline] = None):
        self.documents = list(documents)
        self.pipeline = pipeline or AnalysisPipeline([SentenceAnnotator()])

    def __iter__(self):
        for doc in self.documents:
            for ann in self.pipeline.process(doc).select("sentence"):
                yield ann.text
