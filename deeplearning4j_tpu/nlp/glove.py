"""GloVe: global co-occurrence embeddings.

Analog of the reference's models/glove/ (Glove.java + count/ co-occurrence
pipeline, SURVEY §2.7). Co-occurrence counts are accumulated on host (the
reference's RoundCount/CoOccurrenceWriter machinery reduced to a dict),
then training runs as jitted AdaGrad steps over shuffled batches of
(word_i, word_j, log X_ij) triples — the entire weighted least-squares
update for a batch is one fused device step.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _glove_step(w, wc, b, bc, gw, gwc, gb, gbc, rows, cols, logx, weight, lr):
    """AdaGrad step on J = Σ f(X_ij)(w_i·w̃_j + b_i + b̃_j − log X_ij)²."""
    wi, wj = w[rows], wc[cols]                     # [B, D]
    diff = (jnp.sum(wi * wj, -1) + b[rows] + bc[cols] - logx)  # [B]
    fdiff = weight * diff
    dwi = fdiff[:, None] * wj
    dwj = fdiff[:, None] * wi
    # AdaGrad accumulators (scatter-add), then scaled updates
    gw = gw.at[rows].add(dwi * dwi)
    gwc = gwc.at[cols].add(dwj * dwj)
    gb = gb.at[rows].add(fdiff * fdiff)
    gbc = gbc.at[cols].add(fdiff * fdiff)
    w = w.at[rows].add(-lr * dwi / jnp.sqrt(gw[rows] + 1e-8))
    wc = wc.at[cols].add(-lr * dwj / jnp.sqrt(gwc[cols] + 1e-8))
    b = b.at[rows].add(-lr * fdiff / jnp.sqrt(gb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * fdiff / jnp.sqrt(gbc[cols] + 1e-8))
    loss = 0.5 * jnp.sum(weight * diff * diff)
    return w, wc, b, bc, gw, gwc, gb, gbc, loss


class Glove(SequenceVectors):
    """reference: Glove.Builder — xMax/alpha weighting, symmetric window
    co-occurrences, AdaGrad."""

    def __init__(self, x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, shuffle: bool = True, **kwargs):
        kwargs.setdefault("learning_rate", 0.05)
        super().__init__(**kwargs)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        self.last_loss = None

    def _cooccurrences(self, seqs: List[List[int]]
                       ) -> Dict[Tuple[int, int], float]:
        """Distance-weighted co-occurrence counts, vectorized: per
        sequence the left-context pairs come from an offsets grid, then
        one np.unique + bincount reduces all (wi, wj, 1/d) triples —
        the per-pair Python loop collapsed to numpy (same counts)."""
        v = max(self.vocab.num_words(), 1)
        offs = np.arange(1, self.window_size + 1)
        # periodic reduction bounds peak memory at O(unique pairs +
        # reduce_every) instead of materializing every windowed pair of
        # the corpus before one global unique
        reduce_every = 2_000_000
        acc_keys = np.empty(0, np.int64)
        acc_wts = np.empty(0, np.float64)
        pend_k: List[np.ndarray] = []
        pend_w: List[np.ndarray] = []
        pending = 0

        def reduce_pending():
            nonlocal acc_keys, acc_wts, pend_k, pend_w, pending
            if not pend_k:
                return
            keys = np.concatenate([acc_keys] + pend_k)
            wts = np.concatenate([acc_wts] + pend_w)
            acc_keys, inv = np.unique(keys, return_inverse=True)
            acc_wts = np.bincount(inv, weights=wts)
            pend_k, pend_w, pending = [], [], 0

        for idxs in seqs:
            idxs = np.asarray(idxs, np.int64)
            n = len(idxs)
            if n < 2:
                continue
            grid = np.arange(n)[:, None] - offs[None, :]
            valid = grid >= 0
            wi = np.repeat(idxs, valid.sum(axis=1))
            wj = idxs[grid[valid]]
            inc = 1.0 / np.broadcast_to(
                offs, valid.shape)[valid].astype(np.float64)
            pend_k.append(wi * v + wj)
            pend_w.append(inc)
            pending += len(wi)
            if self.symmetric:
                pend_k.append(wj * v + wi)
                pend_w.append(inc)
                pending += len(wi)
            if pending >= reduce_every:
                reduce_pending()
        reduce_pending()
        return {(int(k // v), int(k % v)): float(s)
                for k, s in zip(acc_keys, acc_wts)}

    def fit(self, sequences: Iterable[Sequence[str]]):
        # materialize BEFORE type-sniffing, without list()-ing strings —
        # list("cat") is ['c','a','t'] and would build a character vocab
        seqs = list(sequences)
        if seqs and isinstance(seqs[0], str):
            seqs = [s.split() for s in seqs]
        else:
            seqs = [list(s) for s in seqs]
        if self.vocab is None:
            self.build_vocab(seqs)
        idx_seqs = [self._indices(s) for s in seqs]
        co = self._cooccurrences(idx_seqs)
        if not co:
            raise ValueError("empty co-occurrence set")
        rows = np.fromiter((k[0] for k in co), np.int32, len(co))
        cols = np.fromiter((k[1] for k in co), np.int32, len(co))
        xs = np.fromiter(co.values(), np.float32, len(co))
        logx = np.log(xs)
        weight = np.minimum((xs / self.x_max) ** self.alpha, 1.0)

        n, d = self.vocab.num_words(), self.layer_size
        rng = np.random.default_rng(self.seed)
        # jnp.array (owning copies): _glove_step donates w/wc, and the
        # CPU backend zero-copy adopts numpy temps — a donated adopted
        # buffer is a use-after-free (see SequenceVectors._init_tables)
        w = jnp.array(((rng.random((n, d)) - 0.5) / d).astype(np.float32))
        wc = jnp.array(((rng.random((n, d)) - 0.5) / d).astype(np.float32))
        b = jnp.zeros(n, jnp.float32)
        bc = jnp.zeros(n, jnp.float32)
        gw = jnp.full((n, d), 1e-8, jnp.float32)
        gwc = jnp.full((n, d), 1e-8, jnp.float32)
        gb = jnp.full(n, 1e-8, jnp.float32)
        gbc = jnp.full(n, 1e-8, jnp.float32)

        bs = self.batch_size
        m = len(rows)
        order = np.arange(m)
        for _ep in range(max(1, self.epochs) * max(1, self.iterations)):
            if self.shuffle:
                rng.shuffle(order)
            total = 0.0
            for s in range(0, m, bs):
                sel = order[s:s + bs]
                if len(sel) < bs:   # pad with repeats; weight-0 the pads
                    pad = np.zeros(bs - len(sel), np.int64)
                    wsel = np.concatenate([weight[sel],
                                           np.zeros(bs - len(sel),
                                                    np.float32)])
                    lsel = np.concatenate([logx[sel], logx[pad]])
                    rsel = np.concatenate([rows[sel], rows[pad]])
                    csel = np.concatenate([cols[sel], cols[pad]])
                else:
                    wsel, lsel = weight[sel], logx[sel]
                    rsel, csel = rows[sel], cols[sel]
                (w, wc, b, bc, gw, gwc, gb, gbc, loss) = _glove_step(
                    w, wc, b, bc, gw, gwc, gb, gbc,
                    jnp.asarray(rsel), jnp.asarray(csel),
                    jnp.asarray(lsel), jnp.asarray(wsel),
                    jnp.float32(self.learning_rate))
                total += float(loss)
            self.last_loss = total / m
        # final vectors: w + w̃ (standard GloVe export)
        self.syn0 = w + wc
        self.syn1 = wc
        return self
