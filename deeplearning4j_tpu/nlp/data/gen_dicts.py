"""Generate the bundled CJK dictionaries (run from the repo root).

Chinese: the top entries of jieba's MIT-licensed frequency dictionary
(jieba 0.42.1, https://github.com/fxsjy/jieba — dict.txt), filtered to
multi-character words and written as "word<space>log_freq" (compact,
gzipped). Attribution: jieba's dict.txt is MIT; see its LICENSE.

Japanese: unique surface forms from the ipadic tokenization of Natsume
Soseki's public-domain novel "Botchan" (the tokenizer-output fixture the
reference's Kuromoji port ships for testing:
deeplearning4j-nlp-japanese/src/test/resources/bocchan-ipadic-features
.txt) — a real-text vocabulary for maximum-matching compound splits.
"""

import gzip
import math
import os
import re

HERE = os.path.dirname(os.path.abspath(__file__))


def gen_chinese(top_n: int = 60000):
    import jieba
    src = os.path.join(os.path.dirname(jieba.__file__), "dict.txt")
    rows = []
    with open(src, encoding="utf-8") as fh:
        for line in fh:
            parts = line.split()
            if len(parts) < 2:
                continue
            word, freq = parts[0], int(parts[1])
            if len(word) < 2 or freq < 2:     # single chars are fallback
                continue
            rows.append((word, freq))
    rows.sort(key=lambda t: -t[1])
    rows = rows[:top_n]
    # normalized log-probabilities: each token on a path then costs its
    # information content, so the unigram DP does not prefer splitting a
    # frequent compound into even-more-frequent pieces
    total = sum(f for _, f in rows)
    with gzip.open(os.path.join(HERE, "chinese_freq.txt.gz"), "wt",
                   encoding="utf-8") as fh:
        for w, f in rows:
            fh.write(f"{w} {math.log(f) - math.log(total):.3f}\n")
    print("chinese:", len(rows), "entries; log_total",
          round(math.log(total), 2))


def gen_japanese_pos():
    """POS + reading lexicon from the same ipadic fixture the word list
    uses: "surface<TAB>coarse_pos<TAB>reading" per unique surface
    (majority POS across occurrences; reading from the most frequent
    entry, '*' when ipadic has none). This is the data Kuromoji's
    Token.getPartOfSpeech/getReading expose — round 5 closes the
    morphological-analysis gap (VERDICT r4 missing #4)."""
    from collections import Counter, defaultdict
    src = ("/root/reference/deeplearning4j-nlp-parent/"
           "deeplearning4j-nlp-japanese/src/test/resources/"
           "bocchan-ipadic-features.txt")
    jp = re.compile(r"^[぀-ヿ一-鿿ー]+$")
    seen = defaultdict(Counter)
    with open(src, encoding="utf-8") as fh:
        for line in fh:
            parts = line.rstrip("\n").split("\t", 1)
            if len(parts) != 2:
                continue
            surface = parts[0].strip()
            if not surface or not jp.match(surface):
                continue
            feats = parts[1].split(",")
            pos = feats[0]
            reading = feats[7] if len(feats) > 7 else "*"
            seen[surface][(pos, reading)] += 1
    with gzip.open(os.path.join(HERE, "japanese_pos.txt.gz"), "wt",
                   encoding="utf-8") as fh:
        for surface in sorted(seen):
            (pos, reading), _n = seen[surface].most_common(1)[0]
            fh.write(f"{surface}\t{pos}\t{reading}\n")
    print("japanese_pos:", len(seen), "entries")


def gen_japanese():
    src = ("/root/reference/deeplearning4j-nlp-parent/"
           "deeplearning4j-nlp-japanese/src/test/resources/"
           "bocchan-ipadic-features.txt")
    words = set()
    jp = re.compile(r"^[぀-ヿ一-鿿ー]+$")
    with open(src, encoding="utf-8") as fh:
        for line in fh:
            surface = line.split("\t", 1)[0].strip()
            if len(surface) >= 2 and jp.match(surface):
                words.add(surface)
    with gzip.open(os.path.join(HERE, "japanese_words.txt.gz"), "wt",
                   encoding="utf-8") as fh:
        for w in sorted(words):
            fh.write(w + "\n")
    print("japanese:", len(words), "entries")


if __name__ == "__main__":
    gen_chinese()
    gen_japanese()
    gen_japanese_pos()
