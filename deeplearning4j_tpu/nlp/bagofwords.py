"""Bag-of-words / TF-IDF vectorizers.

Analog of the reference's bagofwords/vectorizer/ (BagOfWordsVectorizer,
TfidfVectorizer — SURVEY §2.7): corpus → fixed-width count or tf-idf
feature matrix over the vocab, suitable as DataSet features.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BagOfWordsVectorizer:
    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Iterable[str] = ()):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = stop_words
        self.vocab: Optional[VocabCache] = None

    def fit(self, corpus: Iterable[str]):
        tokens = [self.tokenizer_factory.create(s).get_tokens()
                  for s in corpus]
        self.vocab = VocabConstructor(
            self.min_word_frequency, self.stop_words).build_vocab(tokens)
        self._post_fit(tokens)
        return self

    def _post_fit(self, token_lists: List[List[str]]):
        pass

    def transform(self, corpus: Iterable[str]) -> np.ndarray:
        out = []
        for s in corpus:
            row = np.zeros(self.vocab.num_words(), np.float32)
            for tok in self.tokenizer_factory.create(s).get_tokens():
                idx = self.vocab.index_of(tok)
                if idx >= 0:
                    row[idx] += 1.0
            out.append(self._weight(row))
        return np.stack(out) if out else np.zeros(
            (0, self.vocab.num_words()), np.float32)

    def _weight(self, counts: np.ndarray) -> np.ndarray:
        return counts

    def fit_transform(self, corpus: Iterable[str]) -> np.ndarray:
        docs = list(corpus)
        self.fit(docs)
        return self.transform(docs)


class TfidfVectorizer(BagOfWordsVectorizer):
    """tf-idf weighting with smooth idf (reference: TfidfVectorizer.java)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._idf: Optional[np.ndarray] = None

    def _post_fit(self, token_lists: List[List[str]]):
        n_docs = max(1, len(token_lists))
        df = np.zeros(self.vocab.num_words(), np.float64)
        for toks in token_lists:
            for idx in {self.vocab.index_of(t) for t in toks}:
                if idx >= 0:
                    df[idx] += 1
        self._idf = np.log((1 + n_docs) / (1 + df)) + 1.0

    def _weight(self, counts: np.ndarray) -> np.ndarray:
        tf = counts / max(1.0, counts.sum())
        return (tf * self._idf).astype(np.float32)
