"""Fused host pair generation for the embedding producers (ROADMAP #3).

PERF_ANALYSIS r6 closed the corpus-level Python producer at 600-825k
tokens/s against the ~1.5M tokens/s device sink, with ~40% of the
remaining time in ``draw_negatives`` — the loop the reference keeps
native (SkipGram.java:176, SURVEY §2.14's libnd4j host runtime). This
module is the TPU-shaped answer: ONE pass fusing frequent-word
subsampling, the randomized window walk and the negative-table draws
(the work ``SequenceVectors._window_slabs`` + ``skipgram.draw_negatives``
did as separate numpy stages) in ``native/dl4j_native.cpp``, with a
bitwise-identical numpy fallback so the framework works — and trains the
same model — without a toolchain.

PRNG: counter-based splitmix64. Every uniform is ``mix(seed + (k+1) *
GOLDEN)`` for a *counter* k, so there is no sequential generator state
to keep in lockstep between C and numpy — equal (seed, counter) means
equal draw by construction, which is what makes the native/fallback
bitwise-equality contract trivial to hold and to test. Counters are
deterministic functions of corpus position:

- subsample: the token's flat-corpus index
- window ``b``: the kept-token index t
- negatives: ``pair_index * n_neg + slot`` on the primary stream, the
  SAME counter on the redraw stream; a double collision cycles to
  ``(positive + 1) % max(n_words, 2)`` (draw_negatives' policy)

Per-epoch stream seeds are derived host-side (``stream_seed``) and the
final uint64 handed to C, so the two implementations never re-derive
anything independently.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.utils import native

GOLDEN = 0x9E3779B97F4A7C15
M1 = 0xBF58476D1CE4E5B9
M2 = 0x94D049BB133111EB
_MASK = (1 << 64) - 1
_U53 = 1.0 / 9007199254740992.0          # 2**-53

# per-epoch stream phases (see stream_seed)
PHASE_SUB, PHASE_WIN, PHASE_NEG, PHASE_NEG2 = 1, 2, 3, 4

# walk slab: smaller than _window_slabs' 1<<20 because the fused path
# also carries an (n_pairs, n_neg) int32 negatives buffer per slab
SLAB = 1 << 17


# ---------------------------------------------------------------------------
# splitmix64, twice: scalar Python (seed derivation) and vectorized
# numpy uint64 (the fallback draw streams — unsigned wraparound matches
# C's modular arithmetic bit for bit).
# ---------------------------------------------------------------------------

def _mix_int(z: int) -> int:
    z &= _MASK
    z ^= z >> 30
    z = (z * M1) & _MASK
    z ^= z >> 27
    z = (z * M2) & _MASK
    z ^= z >> 31
    return z


def stream_seed(base: int, epoch: int, phase: int) -> int:
    """The per-(epoch, phase) stream seed — computed HERE for both
    backends, so C never derives seeds on its own."""
    return _mix_int(_mix_int((base + GOLDEN * (epoch + 1)) & _MASK)
                    ^ ((phase * M2) & _MASK))


def base_seed(model_seed: int) -> int:
    """The fused producer's root seed, split off the model seed so the
    fused streams are independent of the model's ``_rng`` consumption."""
    return _mix_int((model_seed & _MASK) ^ 0x5041495247454E00)  # "PAIRGEN"


def _mix_np(z: np.ndarray) -> np.ndarray:
    z ^= z >> np.uint64(30)
    z *= np.uint64(M1)
    z ^= z >> np.uint64(27)
    z *= np.uint64(M2)
    z ^= z >> np.uint64(31)
    return z


def draws_at(seed: int, k: np.ndarray) -> np.ndarray:
    """Vectorized draw(seed, k) for a uint64 counter array."""
    k = np.asarray(k, np.uint64)  # host-sync-ok: host counter array
    return _mix_np(np.uint64(seed)
                   + (k + np.uint64(1)) * np.uint64(GOLDEN))


def unit(draw: np.ndarray) -> np.ndarray:
    """53-bit uniform in [0,1) — same construction as C's sm_unit."""
    return (draw >> np.uint64(11)).astype(np.float64) * _U53


def range_reduce(draw: np.ndarray, m: int) -> np.ndarray:
    """Draw -> [0, m), m < 2^32: multiply-shift on the top 32 bits —
    C's sm_range, chosen over '%' because a hardware divide per draw
    dominates the native negative-sampling loop. top32 * m < 2^64, so
    plain uint64 arithmetic here is bitwise-identical to C."""
    return ((draw >> np.uint64(32)) * np.uint64(m)) >> np.uint64(32)


def sm64_fill(seed: int, start: int, n: int, *,
              force_numpy: bool = False) -> np.ndarray:
    """Raw draws at counters [start, start+n) — the parity probe."""
    if not force_numpy:
        out = native.sm64_fill(seed, start, n)
        if out is not None:
            return out
    return draws_at(seed, np.arange(start, start + n, dtype=np.uint64))


# ---------------------------------------------------------------------------
# Fused kernels: native when available, numpy fallback bitwise-equal.
# ---------------------------------------------------------------------------

def keep_probs(vocab, sampling: float) -> np.ndarray:
    """Per-word keep probability (word2vec.c's subsampling formula) —
    the per-token ``_subsample_mask`` arithmetic hoisted to one
    per-vocab-word precompute (values > 1 simply always keep)."""
    counts = np.zeros(vocab.num_words(), np.float64)
    for vw in vocab.vocab_words():
        counts[vw.index] = vw.count
    total = max(1, vocab.total_word_count)
    f = counts / total
    return (np.sqrt(f / sampling) + 1) * sampling / np.maximum(f, 1e-300)


def subsample(ids: np.ndarray, keep_p: np.ndarray, seed: int, *,
              force_numpy: bool = False) -> np.ndarray:
    """Boolean keep mask over the flat corpus, counter = token index."""
    if not force_numpy:
        out = native.pairgen_subsample(ids, keep_p, seed)
        if out is not None:
            return out
    u = unit(draws_at(seed, np.arange(len(ids), dtype=np.uint64)))
    return u < keep_p[ids]


def negatives(table: np.ndarray, positive: np.ndarray, n_neg: int,
              n_words: int, nseed: int, n2seed: int, pair_base: int, *,
              force_numpy: bool = False) -> np.ndarray:
    """(n, n_neg) negative draws for pairs [pair_base, pair_base+n)."""
    if not force_numpy:
        out = native.pairgen_negatives(table, positive, n_neg, n_words,
                                       nseed, n2seed, pair_base)
        if out is not None:
            return out
    n = len(positive)
    q = (np.arange(pair_base, pair_base + n, dtype=np.uint64)[:, None]
         * np.uint64(n_neg)
         + np.arange(n_neg, dtype=np.uint64)[None, :])
    tlen = len(table)
    neg = table[range_reduce(draws_at(nseed, q), tlen)
                .astype(np.int64)].astype(np.int32)
    pos = np.ascontiguousarray(positive, np.int32).reshape(-1, 1)
    coll = neg == pos
    if coll.any():
        # redraw ONLY colliding cells, from the second stream at the
        # SAME counter — the property that keeps this vectorizable
        q2 = np.broadcast_to(q, coll.shape)[coll]
        redrawn = table[range_reduce(draws_at(n2seed, q2), tlen)
                        .astype(np.int64)]
        neg[coll] = redrawn.astype(np.int32)
        cyc = max(n_words, 2)
        neg = np.where(neg == pos,
                       ((pos + 1) % cyc).astype(np.int32), neg)
    return neg


def _window_geometry(pos: np.ndarray, length: np.ndarray, lo: int,
                     hi: int, window: int, wseed: int,
                     n_total: int) -> Tuple[np.ndarray, np.ndarray]:
    """The (slab, 2W) clipped context grid and validity mask — the same
    offsets-grid construction _window_slabs used, with ``b`` from the
    WIN counter stream instead of the model rng."""
    t = np.arange(lo, hi, dtype=np.int64)
    if window > 1:
        b = (np.uint64(1)
             + range_reduce(draws_at(wseed, t.astype(np.uint64)),
                            window)).astype(np.int32)
    else:
        b = np.ones(hi - lo, np.int32)
    offsets = np.concatenate([np.arange(-window, 0),
                              np.arange(1, window + 1)]).astype(np.int32)
    po = pos[lo:hi, None] + offsets[None, :]
    valid = ((np.abs(offsets)[None, :] <= b[:, None])
             & (po >= 0) & (po < length[lo:hi, None]))
    grid = t[:, None] + offsets[None, :]
    np.clip(grid, 0, n_total - 1, out=grid)
    return grid, valid


def walk(ids: np.ndarray, pos: np.ndarray, length: np.ndarray, lo: int,
         hi: int, window: int, wseed: int, *,
         table: Optional[np.ndarray] = None, n_neg: int = 0,
         n_words: int = 0, nseed: int = 0, n2seed: int = 0,
         pair_base: int = 0, force_numpy: bool = False
         ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """The fused SGNS/HS/DBOW window walk over kept-token slab [lo, hi):
    returns (centers, contexts, negs) with negs None when n_neg == 0.
    Pair order is ascending offset per center — identical to the numpy
    offsets-grid flatten. ``pair_base`` is the epoch-global pair counter
    feeding the NEG streams."""
    cap = (hi - lo) * 2 * window
    if not force_numpy and native.pairgen_available():
        out_c = np.empty(cap, np.int32)
        out_x = np.empty(cap, np.int32)
        out_n = np.empty((cap, n_neg), np.int32) if n_neg > 0 else None
        got = native.pairgen_walk(ids, pos, length, lo, hi, window,
                                  wseed, table, n_neg, n_words, nseed,
                                  n2seed, pair_base, out_c, out_x, out_n)
        if got is not None:
            return (out_c[:got], out_x[:got],
                    out_n[:got] if out_n is not None else None)
    grid, valid = _window_geometry(pos, length, lo, hi, window, wseed,
                                   len(ids))
    centers = np.repeat(ids[lo:hi], valid.sum(axis=1))
    contexts = ids[grid[valid]]
    negs = None
    if n_neg > 0:
        negs = negatives(table, contexts, n_neg, n_words, nseed, n2seed,
                         pair_base, force_numpy=True)
    return centers, contexts, negs


def walk_cbow(ids: np.ndarray, pos: np.ndarray, length: np.ndarray,
              lo: int, hi: int, window: int, wseed: int, *,
              table: Optional[np.ndarray] = None, n_neg: int = 0,
              n_words: int = 0, nseed: int = 0, n2seed: int = 0,
              row_base: int = 0, force_numpy: bool = False
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                         Optional[np.ndarray]]:
    """The fused CBOW row walk: returns (ctx, cmask, centers, negs) for
    the centers in [lo, hi) that have >= 1 valid context. ``row_base``
    is the epoch-global EMITTED-row counter (skipped centers do not
    advance it)."""
    cw = 2 * window
    if not force_numpy and native.pairgen_available():
        cap = hi - lo
        out_ctx = np.empty((cap, cw), np.int32)
        out_m = np.empty((cap, cw), np.float32)
        out_c = np.empty(cap, np.int32)
        out_n = np.empty((cap, n_neg), np.int32) if n_neg > 0 else None
        got = native.pairgen_walk_cbow(ids, pos, length, lo, hi, window,
                                       wseed, table, n_neg, n_words,
                                       nseed, n2seed, row_base, out_ctx,
                                       out_m, out_c, out_n)
        if got is not None:
            return (out_ctx[:got], out_m[:got], out_c[:got],
                    out_n[:got] if out_n is not None else None)
    grid, valid = _window_geometry(pos, length, lo, hi, window, wseed,
                                   len(ids))
    keep = valid.any(axis=1)
    ctx = ids[grid][keep]
    cmask = valid[keep].astype(np.float32)
    centers = ids[lo:hi][keep]
    negs = None
    if n_neg > 0:
        negs = negatives(table, centers, n_neg, n_words, nseed, n2seed,
                         row_base, force_numpy=True)
    return ctx, cmask, centers, negs


# ---------------------------------------------------------------------------
# The model-facing walker: per-fit precompute + per-epoch subsampled
# views, mirroring _window_slabs' anneal-accounting contract.
# ---------------------------------------------------------------------------

def _positions(seq_id: np.ndarray):
    # deferred: sequence_vectors imports this module lazily inside its
    # fused producers, so a top-level import here would be circular
    from deeplearning4j_tpu.nlp.sequence_vectors import _corpus_positions
    return _corpus_positions(seq_id)


class EpochView:
    """One epoch's kept corpus: ids/pos/length after the SUB-stream
    subsample, plus the epoch's WIN/NEG/NEG2 stream seeds. ``n < 2``
    means the epoch is too short to window (producers advance their
    token accounting and move on, like _window_slabs' degenerate
    yield)."""

    def __init__(self, walker: "CorpusWalker", epoch: int):
        w = self.walker = walker
        self.wseed = stream_seed(w.base, epoch, PHASE_WIN)
        self.nseed = stream_seed(w.base, epoch, PHASE_NEG)
        self.n2seed = stream_seed(w.base, epoch, PHASE_NEG2)
        if w.keep_p is not None:
            m = subsample(w.ids_all, w.keep_p,
                          stream_seed(w.base, epoch, PHASE_SUB),
                          force_numpy=w.force_numpy)
            self.ids = w.ids_all[m]
            seq_id = w.seq_all[m]
            self.extras = (tuple(e[m] for e in w.extras)
                           if w.extras is not None else None)
        else:
            self.ids, seq_id = w.ids_all, w.seq_all
            self.extras = w.extras
        self.n = len(self.ids)
        if self.n >= 2:
            self.pos, self.length = _positions(seq_id)
        else:
            self.pos = self.length = None

    def slab_bounds(self):
        for lo in range(0, self.n, self.walker.slab):
            yield lo, min(self.n, lo + self.walker.slab)

    def walk(self, lo: int, hi: int, *, n_neg: int = 0,
             pair_base: int = 0):
        w = self.walker
        out = walk(self.ids, self.pos, self.length, lo, hi, w.window,
                   self.wseed, table=w.table, n_neg=n_neg,
                   n_words=w.n_words, nseed=self.nseed,
                   n2seed=self.n2seed, pair_base=pair_base,
                   force_numpy=w.force_numpy)
        w._count(hi - lo, len(out[0]))
        return out

    def walk_cbow(self, lo: int, hi: int, *, n_neg: int = 0,
                  row_base: int = 0):
        w = self.walker
        out = walk_cbow(self.ids, self.pos, self.length, lo, hi,
                        w.window, self.wseed, table=w.table,
                        n_neg=n_neg, n_words=w.n_words,
                        nseed=self.nseed, n2seed=self.n2seed,
                        row_base=row_base, force_numpy=w.force_numpy)
        w._count(hi - lo, len(out[2]))
        return out

    def negatives(self, positive: np.ndarray, n_neg: int,
                  pair_base: int) -> np.ndarray:
        """NEG-stream draws for producer-shaped pairs outside the walk
        (DBOW's label rows), sharing the epoch's global pair counter."""
        w = self.walker
        return negatives(w.table, positive, n_neg, w.n_words,
                         self.nseed, self.n2seed, pair_base,
                         force_numpy=w.force_numpy)


class CorpusWalker:
    """Per-fit fused pair generator. Owns the precompute (keep
    probabilities, int32 unigram table, stream base seed) and hands out
    per-epoch ``EpochView``s; the mode-specific producers in nlp/ drive
    the slab loop and feed _PairStream. ``force_numpy=True`` pins the
    bitwise-identical fallback (the ``pairgen="numpy"`` knob and the
    A/B bench's reference arm)."""

    def __init__(self, model, ids_all: np.ndarray, seq_all: np.ndarray,
                 *, extras=None, slab: int = SLAB,
                 force_numpy: bool = False):
        self.ids_all = np.ascontiguousarray(ids_all, np.int32)
        self.seq_all = seq_all
        self.extras = extras
        self.slab = slab
        self.force_numpy = force_numpy or not native.pairgen_available()
        self.window = model.window_size
        self.n_words = model.vocab.num_words()
        self.base = base_seed(model.seed)
        self.keep_p = (keep_probs(model.vocab, model.sampling)
                       if model.sampling > 0 else None)
        tbl = getattr(model, "_table", None)
        self.table = (np.ascontiguousarray(tbl, np.int32)
                      if tbl is not None else None)
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = default_registry()
        self._c_tokens = reg.counter(
            "dl4j_pairgen_tokens_total",
            "corpus tokens walked by the fused pair generator")
        self._c_pairs = reg.counter(
            "dl4j_pairgen_pairs_total",
            "(center, context) pairs / CBOW rows emitted by the fused "
            "pair generator")
        self._path = "numpy" if self.force_numpy else "native"

    def _count(self, tokens: int, pairs: int):
        # telemetry counts are plain host ints
        self._c_tokens.inc(float(tokens),  # host-sync-ok: host int
                           path=self._path)
        self._c_pairs.inc(float(pairs),  # host-sync-ok: host int
                          path=self._path)

    def epoch(self, epoch: int) -> EpochView:
        return EpochView(self, epoch)
