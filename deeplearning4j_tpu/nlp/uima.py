"""UIMA type system + XMI serialization.

Completes the UIMA surface started in ``nlp/language_packs.py`` (CAS /
Annotation / AnalysisEngine): the reference vendors Apache UIMA in
``deeplearning4j-nlp-parent/deeplearning4j-nlp-uima`` whose two
interchange artifacts are the *type system descriptor* (XML) and *XMI*
(XML Metadata Interchange) CAS serialization. This module implements
both against the same in-memory CAS:

- ``TypeSystem``: named annotation types with single inheritance and
  typed features; ``validate`` checks a CAS against it.
- ``to_xmi`` / ``from_xmi``: round-trip a CAS through standards-shaped
  XMI (xmi:XMI envelope, ``cas:Sofa`` holding the document text,
  one element per annotation carrying ``xmi:id``/``begin``/``end`` and
  feature attributes).
- ``type_system_xml``: the descriptor XML for interchange with real UIMA
  installations.

Pure stdlib (xml.etree); no Java, no uimaj — the data formats are the
compatibility surface, not the JVM runtime.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.nlp.language_packs import CAS, Annotation

_NS = {
    "xmi": "http://www.omg.org/XMI",
    "cas": "http:///uima/cas.ecore",
    "dl4j": "http:///deeplearning4j_tpu.ecore",
}


class TypeDescription:
    """One annotation type: name, supertype, feature -> range type."""

    def __init__(self, name: str, supertype: str = "uima.tcas.Annotation",
                 features: Optional[Dict[str, str]] = None):
        self.name = name
        self.supertype = supertype
        self.features = dict(features or {})


class TypeSystem:
    """Single-inheritance annotation type registry (the UIMA
    TypeSystemDescription analog)."""

    def __init__(self, types: Sequence[TypeDescription] = ()):
        self.types: Dict[str, TypeDescription] = {}
        for t in types:
            self.add(t)

    def add(self, t: TypeDescription) -> "TypeSystem":
        if t.name in self.types:
            raise ValueError(f"duplicate type {t.name!r}")
        self.types[t.name] = t
        return self

    def _chain(self, name: str) -> List[TypeDescription]:
        """Supertype chain with cycle detection (a hand-edited external
        descriptor can declare A<-B<-A; report it, don't hang)."""
        seen = set()
        chain = []
        while name in self.types:
            if name in seen:
                raise ValueError(f"type system has a supertype cycle at"
                                 f" {name!r}")
            seen.add(name)
            chain.append(self.types[name])
            name = self.types[name].supertype
        return chain

    def subsumes(self, ancestor: str, name: str) -> bool:
        if name == ancestor:
            return True
        return any(t.name == ancestor or t.supertype == ancestor
                   for t in self._chain(name))

    def features_of(self, name: str) -> Dict[str, str]:
        """Own + inherited features."""
        out: Dict[str, str] = {}
        for t in reversed(self._chain(name)):
            out.update(t.features)
        return out

    def validate(self, cas: CAS) -> List[str]:
        """Return problems (empty = valid): unknown types, unknown
        features, spans out of bounds."""
        problems = []
        n = len(cas.text)
        for tname in list(getattr(cas, "_by_type", {})):
            if tname not in self.types:
                problems.append(f"unknown type: {tname}")
                continue
            allowed = set(self.features_of(tname))
            for ann in cas.select(tname):
                if not (0 <= ann.begin <= ann.end <= n):
                    problems.append(
                        f"{tname} span [{ann.begin},{ann.end}) outside"
                        f" document of length {n}")
                for feat in ann.features:
                    if feat not in allowed:
                        problems.append(
                            f"{tname} has undeclared feature {feat!r}")
        return problems

    # ---- descriptor XML -------------------------------------------------
    def to_xml(self) -> str:
        root = ET.Element("typeSystemDescription")
        types_el = ET.SubElement(root, "types")
        for t in self.types.values():
            te = ET.SubElement(types_el, "typeDescription")
            ET.SubElement(te, "name").text = t.name
            ET.SubElement(te, "supertypeName").text = t.supertype
            if t.features:
                fs = ET.SubElement(te, "features")
                for fname, frange in t.features.items():
                    fe = ET.SubElement(fs, "featureDescription")
                    ET.SubElement(fe, "name").text = fname
                    ET.SubElement(fe, "rangeTypeName").text = frange
        return ET.tostring(root, encoding="unicode")

    @classmethod
    def from_xml(cls, xml: str) -> "TypeSystem":
        root = ET.fromstring(xml)
        ts = cls()
        for te in root.iter("typeDescription"):
            feats = {}
            for fe in te.iter("featureDescription"):
                feats[fe.findtext("name")] = fe.findtext("rangeTypeName")
            ts.add(TypeDescription(te.findtext("name"),
                                   te.findtext("supertypeName")
                                   or "uima.tcas.Annotation", feats))
        return ts


DEFAULT_TYPE_SYSTEM = TypeSystem([
    TypeDescription("sentence"),
    TypeDescription("token", features={"pos": "uima.cas.String",
                                       "lemma": "uima.cas.String"}),
])


import re as _re

_RESERVED_ATTRS = frozenset({"sofa", "begin", "end"})
_XML_NAME = _re.compile(r"^[A-Za-z_][A-Za-z0-9_.\-]*$")


def to_xmi(cas: CAS) -> str:
    """Serialize a CAS to XMI: xmi:XMI envelope, cas:Sofa with the
    document text, one dl4j:<type> element per annotation. Feature names
    must be valid XML attribute names and may not shadow the reserved
    span attributes (sofa/begin/end) — violations raise rather than
    silently corrupting the spans."""
    for prefix, uri in _NS.items():
        ET.register_namespace(prefix, uri)
    root = ET.Element(f"{{{_NS['xmi']}}}XMI",
                      {f"{{{_NS['xmi']}}}version": "2.0"})
    next_id = 1
    sofa = ET.SubElement(root, f"{{{_NS['cas']}}}Sofa", {
        f"{{{_NS['xmi']}}}id": str(next_id),
        "sofaNum": "1",
        "sofaID": "_InitialView",
        "mimeType": "text",
        "sofaString": cas.text,
    })
    sofa_id = next_id
    next_id += 1
    for tname in sorted(getattr(cas, "_by_type", {})):
        for ann in cas.select(tname):
            attrs = {
                f"{{{_NS['xmi']}}}id": str(next_id),
                "sofa": str(sofa_id),
                "begin": str(ann.begin),
                "end": str(ann.end),
            }
            for k, v in ann.features.items():
                if k in _RESERVED_ATTRS or not _XML_NAME.match(k):
                    raise ValueError(
                        f"feature name {k!r} on {tname!r} cannot be "
                        "serialized to XMI (reserved or not a valid XML"
                        " attribute name)")
                attrs[k] = str(v)
            ET.SubElement(root, f"{{{_NS['dl4j']}}}{tname}", attrs)
            next_id += 1
    return ET.tostring(root, encoding="unicode")


def from_xmi(xml: str,
             type_system: Optional[TypeSystem] = None) -> CAS:
    """Parse XMI back into a CAS; validates against ``type_system`` when
    given (raises ValueError listing the problems)."""
    root = ET.fromstring(xml)
    sofa = root.find(f"{{{_NS['cas']}}}Sofa")
    if sofa is None:
        raise ValueError("XMI has no cas:Sofa element")
    text = sofa.get("sofaString", "")
    cas = CAS(text)
    reserved = {"sofa", "begin", "end"}
    for el in root:
        if el is sofa:
            continue
        tag = el.tag
        if not tag.startswith(f"{{{_NS['dl4j']}}}"):
            continue
        tname = tag[len(f"{{{_NS['dl4j']}}}"):]
        begin = int(el.get("begin", 0))
        end = int(el.get("end", 0))
        feats = {k: v for k, v in el.attrib.items()
                 if k not in reserved and not k.startswith("{")}
        cas.add(Annotation(tname, begin, end, text[begin:end], **feats))
    if type_system is not None:
        problems = type_system.validate(cas)
        if problems:
            raise ValueError("XMI fails type-system validation: "
                             + "; ".join(problems))
    return cas
