"""Word2Vec: SkipGram/CBOW over text corpora.

Analog of the reference's models/word2vec/Word2Vec.java:32 (extends
SequenceVectors) — adds the text front-end: a SentenceIterator +
TokenizerFactory turn raw text into token sequences, then training is
SequenceVectors' device hot loop (nlp/skipgram.py).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.nlp import skipgram as sk
from deeplearning4j_tpu.nlp.sentence_iterators import SentenceIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)

import jax.numpy as jnp


class Word2Vec(SequenceVectors):
    """reference: Word2Vec.Builder — same knob names (layerSize →
    layer_size, windowSize → window_size, minWordFrequency, negative,
    useHierarchicSoftmax, elementsLearningAlgorithm SkipGram/CBOW)."""

    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()

    # ---- text front-end --------------------------------------------------
    def _tokenize(self, corpus) -> List[List[str]]:
        # materialize first so type-sniffing can't consume a generator
        items = corpus if isinstance(corpus, list) else list(corpus)
        if items and isinstance(items[0], str):
            return [self.tokenizer_factory.create(s).get_tokens()
                    for s in items]
        if items and all(isinstance(s, list) for s in items):
            return items       # already token lists: no 3M-token copy
        return [list(s) for s in items]

    def fit(self, corpus: Union[SentenceIterator, Iterable[str],
                                Iterable[Sequence[str]]]):
        return super().fit(self._tokenize(corpus))

    def fit_stream(self, sentences: Iterable[str], *,
                   window_sentences: int = 1000,
                   max_windows: Optional[int] = None,
                   on_window=None) -> "Word2Vec":
        """Train from an UNBOUNDED sentence stream (a
        StreamingSentenceIterator or a follow-mode
        CorpusDataSetIterator) in windows of ``window_sentences``:

        - the FIRST window builds the vocab, which is then fixed —
          stable syn0/syn1 geometry means downstream consumers
          (OnlineServing promotion) never see a shape change, so
          refreshed embeddings hot-swap with zero recompiles; later
          out-of-vocab tokens are dropped like any sub-min-frequency
          word
        - every window then runs one full ``fit`` pass over its
          sentences (``epochs`` per window, lr re-annealing per window
          — the streaming analog of restarting the linear decay each
          corpus revision)
        - ``on_window(model, index, n_sentences)`` fires after each
          window — the hot-promotion hook

        Consumes until the stream ends (EOS / idle timeout / stream
        cap) or ``max_windows`` windows. Returns self."""
        import itertools
        it = iter(sentences)
        wi = 0
        while max_windows is None or wi < max_windows:
            batch = list(itertools.islice(it, window_sentences))
            if not batch:
                break
            self.fit(batch)
            wi += 1
            if on_window is not None:
                on_window(self, wi - 1, len(batch))
        return self

    def build_vocab(self, corpus, special_tokens: Iterable[str] = ()):
        return super().build_vocab(self._tokenize(corpus),
                                   special_tokens=special_tokens)

    # ---- CBOW training path ---------------------------------------------
    def _train_sequence(self, idxs, batcher, seen, total):
        if not self.use_cbow:
            return super()._train_sequence(idxs, batcher, seen, total)
        # CBOW: context window predicts center (reference: CBOW.java via
        # AggregateCBOW). Batched separately because the h-vector is a
        # masked mean over context rows.
        window = self.window_size
        ctx_w = 2 * window
        if not hasattr(self, "_cbow_buf") or self._cbow_buf is None:
            self._cbow_buf = _CbowBatcher(self.batch_size, ctx_w, self._k())
        buf = self._cbow_buf
        for pos, center in enumerate(idxs):
            lo, hi = self._window_bounds(pos, len(idxs))
            ctx = [idxs[c] for c in range(lo, hi) if c != pos]
            if not ctx:
                seen += 1
                continue
            if self.use_hs:
                targets, labels = sk.hs_targets(
                    self.vocab.element_at_index(center))
            else:
                targets, labels = sk.negative_sample_targets(
                    center, self._table, self.negative, self._rng)
            if buf.add(ctx, targets, labels):
                self._flush_cbow(buf, self._lr(seen, total))
            seen += 1
        return seen

    def fit_finalize(self):
        pass

    def _flush(self, batcher, lr):
        super()._flush(batcher, lr)
        if getattr(self, "_cbow_buf", None) is not None:
            self._flush_cbow(self._cbow_buf, lr)

    def _flush_cbow(self, buf: "_CbowBatcher", lr: float):
        if buf.n == 0 and buf.mask.sum() == 0:
            return
        ctx, cmask, targets, labels, mask = buf.take()
        self.syn0, self.syn1 = sk.cbow_step(
            self.syn0, self.syn1, jnp.asarray(ctx), jnp.asarray(cmask),
            jnp.asarray(targets), jnp.asarray(labels), jnp.asarray(mask),
            jnp.float32(lr))

    # non-CBOW calls delegate straight to the base hook, so the vectorized
    # SGNS fast path stays valid for Word2Vec (see _fast_sgns_ok)
    _train_sequence._sgns_fast_path_safe = True

    def _dispatch_chunks(self, prep):
        """Adds the CBOW superchunk kinds to the base consumer (same
        prepare/dispatch split — see SequenceVectors._dispatch_chunks)."""
        kind = prep[0]
        if kind == "cbow_hs":
            _, ctx, cmask, cen, nv, lrs = prep
            self.syn0, self.syn1 = sk.cbow_hs_scan_step(
                self.syn0, self.syn1, jnp.asarray(ctx),
                jnp.asarray(cmask), jnp.asarray(cen), self._hs_points,
                self._hs_labels, self._hs_mask, jnp.asarray(nv),
                jnp.asarray(lrs))
        elif kind == "cbow_ns":
            _, ctx, cmask, tgt, nv, lrs = prep
            self.syn0, self.syn1 = sk.cbow_scan_step(
                self.syn0, self.syn1, jnp.asarray(ctx),
                jnp.asarray(cmask), jnp.asarray(tgt), jnp.asarray(nv),
                jnp.asarray(lrs))
        else:
            super()._dispatch_chunks(prep)

    def _fit_fast_cbow(self, seqs, total_words: int,
                       extra_per_seq=None):
        """Vectorized CBOW (NS and HS): context windows built with the
        same numpy offsets grid the SGNS fast path uses, one donated
        ``cbow_step`` per chunk — replaces the per-center Python loop
        (reference: AggregateCBOW batching, CBOW.java).

        ``extra_per_seq``: per-sequence id lists appended to every
        center's context window — ParagraphVectors' DM mode (the doc
        label vectors join each context)."""
        rng = self._rng
        if self.device_pair_generation:
            import warnings
            warnings.warn(
                "device_pair_generation does not cover CBOW; using the "
                "host context-window pipeline", stacklevel=2)
        W = self.window_size
        max_extra = (max((len(e) for e in extra_per_seq), default=0)
                     if extra_per_seq else 0)
        ctx_w = 2 * W + max_extra
        from deeplearning4j_tpu.nlp.sequence_vectors import _PairStream
        chunk = self._pair_chunk_size(total_words)  # one center per token
        depth = _PairStream.DEPTH   # chunks per scanned dispatch
        k = self._k()
        ctx_buf = np.zeros((depth, chunk, ctx_w), np.int32)
        cmask_buf = np.zeros((depth, chunk, ctx_w), np.float32)
        cen_buf = np.zeros((depth, chunk), np.int32)
        nv = np.zeros(depth, np.int32)
        lrs = np.zeros(depth, np.float32)
        hs = self.use_hs
        if hs:
            self._ensure_hs_matrices()
        table = self._table
        n_words = self.vocab.num_words()
        # fused pairgen covers plain CBOW only (DM's per-doc label
        # columns keep the per-sequence producer); with NS its per-row
        # negatives ride the counter streams instead of flush-time draws
        fused = self.pairgen != "legacy" and not max_extra
        n_negf = 0 if (hs or not fused) else k - 1
        negs_buf = (np.zeros((depth, chunk, n_negf), np.int32)
                    if n_negf else None)
        d = 0
        fill = 0
        seen = 0

        def produce(sink):
            nonlocal d, fill, seen

            def flush():
                nonlocal d
                if d == 0:
                    return
                nv[d:] = 0
                lrs[d:] = 0.0
                # .copy(): the loop keeps mutating these buffers
                # (see _fit_fast_sgns)
                if hs:
                    prep = ("cbow_hs", ctx_buf.copy(), cmask_buf.copy(),
                            cen_buf.copy(), nv.copy(), lrs.copy())
                else:
                    tgt = np.zeros((depth, chunk, k), np.int32)
                    tgt[..., 0] = cen_buf
                    if negs_buf is not None:
                        # fused counter-stream draws (nlp/pairgen.py);
                        # rows past nv are inert under the mask
                        tgt[..., 1:] = negs_buf
                    else:
                        flat = tgt.reshape(-1, k)
                        flat[:, 1:] = sk.draw_negatives(
                            rng, table, flat[:, 0:1], k - 1, n_words)
                    prep = ("cbow_ns", ctx_buf.copy(),
                            cmask_buf.copy(), tgt, nv.copy(),
                            lrs.copy())
                d = 0
                sink(prep)

            def seal():
                nonlocal d, fill
                nv[d] = fill
                lrs[d] = self._lr(seen, total_words)
                if fill < chunk:
                    cmask_buf[d, fill:] = 0.0
                d += 1
                fill = 0
                if d == depth:
                    flush()

            def push_rows(cens, ctxs, valids, tokens=0.0, negs=None):
                """``tokens`` of anneal progress spreads evenly over the
                rows (the _PairStream.push contract — advancing ``seen``
                up front snaps small corpora straight to
                min_learning_rate; code-review r4/r5)."""
                nonlocal fill, seen
                p, n = 0, len(cens)
                if n == 0:
                    seen += tokens
                    return
                per = tokens / n
                while p < n:
                    take = min(chunk - fill, n - p)
                    sl = slice(fill, fill + take)
                    seen += per * take
                    cen_buf[d, sl] = cens[p:p + take]
                    ctx_buf[d, sl] = ctxs[p:p + take]
                    cmask_buf[d, sl] = \
                        valids[p:p + take].astype(np.float32)
                    if negs is not None:
                        negs_buf[d, sl] = negs[p:p + take]
                    fill += take
                    p += take
                    if fill == chunk:
                        seal()

            if max_extra:
                # DM: per-sequence loop (label columns vary per doc)
                for _epoch in range(self.epochs):
                    for si, seq in enumerate(seqs):
                        idxs = np.asarray(  # host-sync-ok: host encode
                            self._indices(seq), np.int32)
                        n = len(idxs)
                        # even a 1-token doc trains its label vector
                        if n < 1:
                            continue
                        grid, valid = sk.window_grid(n, W, rng)
                        ctx = idxs[np.clip(grid, 0, n - 1)]
                        e = np.asarray(  # host-sync-ok: host label ids
                            extra_per_seq[si], np.int32)
                        pad = np.zeros(max_extra - len(e), np.int32)
                        ctx = np.concatenate(
                            [ctx,
                             np.tile(np.concatenate([e, pad]), (n, 1))],
                            axis=1)
                        evalid = np.concatenate(
                            [np.ones(len(e), bool),
                             np.zeros(max_extra - len(e), bool)])
                        valid = np.concatenate(
                            [valid, np.tile(evalid, (n, 1))], axis=1)
                        push_rows(idxs, ctx, valid, tokens=n)
            elif fused:
                # fused pairgen (nlp/pairgen.py): subsample + window
                # rows + negatives in one native (or bitwise-equal
                # numpy) pass, row counter = emitted rows per epoch
                from deeplearning4j_tpu.nlp import pairgen as pg
                ids_all, seq_all = self._encode_corpus_flat(seqs)
                walker = pg.CorpusWalker(
                    self, ids_all, seq_all,
                    force_numpy=self.pairgen == "numpy")
                for ep in range(self.epochs):
                    view = walker.epoch(ep)
                    if view.n < 2:
                        seen += view.n
                        continue
                    row_base = 0
                    for lo, hi in view.slab_bounds():
                        ctx, cmask, cens, negs = view.walk_cbow(
                            lo, hi, n_neg=n_negf, row_base=row_base)
                        row_base += len(cens)
                        push_rows(cens, ctx, cmask, tokens=hi - lo,
                                  negs=negs)
            else:
                # plain CBOW (round 5): corpus-level numpy via the SAME
                # window walk the SGNS fast path uses (_window_slabs) —
                # one flat encode, offsets-grid slabs, no per-sequence
                # Python (the measured host bound)
                ids_all, seq_all = self._encode_corpus_flat(seqs)
                for ids, lo, hi, grid, valid in self._window_slabs(
                        ids_all, seq_all):
                    if valid is None:
                        seen += hi - lo
                        continue
                    keep = valid.any(axis=1)   # centers w/ context
                    push_rows(ids[lo:hi][keep], ids[grid][keep],
                              valid[keep], tokens=hi - lo)
            if fill:
                seal()
            flush()

        if self.overlap_pairgen:
            self._run_overlapped(produce)
        else:
            produce(self._dispatch_chunks)
        return self


class _CbowBatcher:
    def __init__(self, batch_size: int, ctx_w: int, k: int):
        self.batch_size, self.ctx_w, self.k = batch_size, ctx_w, k
        self.ctx = np.zeros((batch_size, ctx_w), np.int32)
        self.cmask = np.zeros((batch_size, ctx_w), np.float32)
        self.targets = np.zeros((batch_size, k), np.int32)
        self.labels = np.zeros((batch_size, k), np.float32)
        self.mask = np.zeros((batch_size, k), np.float32)
        self.n = 0

    def add(self, ctx, targets, labels) -> bool:
        i = self.n
        w = min(len(ctx), self.ctx_w)
        self.ctx[i, :w] = ctx[:w]
        self.cmask[i, :w] = 1.0
        self.cmask[i, w:] = 0.0
        kk = min(len(targets), self.k)
        self.targets[i, :kk] = targets[:kk]
        self.labels[i, :kk] = labels[:kk]
        self.mask[i, :kk] = 1.0
        self.mask[i, kk:] = 0.0
        self.n += 1
        return self.n >= self.batch_size

    def take(self):
        out = (self.ctx.copy(), self.cmask.copy(), self.targets.copy(),
               self.labels.copy(), self.mask.copy())
        if self.n < self.batch_size:
            out[4][self.n:] = 0.0
            out[1][self.n:] = 0.0
        self.n = 0
        self.mask[:] = 0.0
        self.cmask[:] = 0.0
        return out


class StaticWord2Vec:
    """Read-only vector lookup (reference: word2vec/StaticWord2Vec.java —
    memory-mapped serving copy without training state)."""

    def __init__(self, words: List[str], vectors: np.ndarray):
        self._index = {w: i for i, w in enumerate(words)}
        self._words = list(words)
        self._vectors = np.asarray(  # host-sync-ok: one-time snapshot
            vectors, np.float32)

    @classmethod
    def from_model(cls, w2v: SequenceVectors) -> "StaticWord2Vec":
        return cls(w2v.vocab.words(), w2v.word_vectors_matrix)

    def has_word(self, w: str) -> bool:
        return w in self._index

    def get_word_vector(self, w: str) -> np.ndarray:
        return self._vectors[self._index[w]]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        den = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / den) if den else 0.0  # host-sync-ok: host numpy
