"""NLP stack: embeddings (Word2Vec/ParagraphVectors/GloVe), text pipeline.

TPU-native analog of deeplearning4j-nlp-parent (SURVEY §2.7,
deeplearning4j-nlp/.../models/). The reference's hot loop delegates
per-pair updates to native "aggregate" ops (SkipGram.java:176
``Nd4j.getExecutioner().exec(batches)``); here pairs are batched on host
and applied in one jitted scatter-add step on device (nlp/skipgram.py).
"""

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    CommonPreprocessor,
)
from deeplearning4j_tpu.nlp.sentence_iterators import (
    BasicLineIterator,
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelledDocument,
    CollectionLabelledDocumentIterator,
)
from deeplearning4j_tpu.nlp.vocab import (
    VocabWord,
    VocabCache,
    VocabConstructor,
    Huffman,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp import serializer as WordVectorSerializer

__all__ = [
    "DefaultTokenizerFactory", "NGramTokenizerFactory", "CommonPreprocessor",
    "BasicLineIterator", "CollectionSentenceIterator", "FileSentenceIterator",
    "LabelledDocument", "CollectionLabelledDocumentIterator",
    "VocabWord", "VocabCache", "VocabConstructor", "Huffman",
    "Word2Vec", "SequenceVectors", "ParagraphVectors", "Glove",
    "WordVectorSerializer",
]
