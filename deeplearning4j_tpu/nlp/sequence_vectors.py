"""SequenceVectors: the generic embedding trainer.

Analog of the reference's models/sequencevectors/SequenceVectors.java:50
(``fit()`` at :193): build a vocab over element sequences, then train
SkipGram/CBOW over windows. Word2Vec, ParagraphVectors and DeepWalk all
specialise this class, exactly as in the reference.

Where the reference fans sequences out to trainer threads that each feed
native aggregate ops (§3.6), the TPU design streams pair batches into the
jitted scatter-add kernels in nlp/skipgram.py — device-bound throughput
with a single Python producer.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import skipgram as sk
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabConstructor


def _corpus_positions(seq_id: np.ndarray):
    """Per-token (position-within-sequence, sequence-length) for a flat
    encoded corpus — ONE numpy pass, no per-sequence loop. Shared by the
    SGNS and CBOW corpus-level pair generators."""
    n = len(seq_id)
    change = np.empty(n, bool)
    change[0] = True
    np.not_equal(seq_id[1:], seq_id[:-1], out=change[1:])
    starts = np.flatnonzero(change)
    seg = np.cumsum(change) - 1
    # int32: the (slab, 2W) window arithmetic downstream is memory
    # bound — half-width indices halve its traffic
    pos = (np.arange(n) - starts[seg]).astype(np.int32)
    lens = np.diff(np.append(starts, n))
    return pos, lens[seg].astype(np.int32)


class _PairStream:
    """Chunked (center, context) consumer for the vectorized SGNS/HS
    paths (used by SequenceVectors and ParagraphVectors' DBOW): buffers
    ``depth`` chunks of pushed pair arrays and flushes them as ONE
    scanned device dispatch (sk.skipgram_scan_step) — the scan applies
    the chunks sequentially (same math as chunk-at-a-time) while
    amortizing the per-dispatch transport overhead depth× and letting
    the host build the next superchunk while the device drains this
    one. ``seen`` is advanced by the producer; the lr anneal snapshots
    it per chunk (word2vec.c's linear decay)."""

    DEPTH = 8

    def __init__(self, model, chunk: int, total_words: int,
                 depth: int = DEPTH, sink=None, n_neg: int = 0):
        self.m = model
        self.chunk = chunk
        self.depth = depth
        self.total = total_words
        self.seen = 0
        self.cen = np.zeros((depth, chunk), np.int32)
        # n_neg > 0: the fused producers (nlp/pairgen.py) push their
        # stream-drawn per-pair negatives alongside the pairs. They are
        # buffered interleaved in the device-shaped (1 + n_neg) target
        # rows (context in column 0), so _flush forwards ONE copy
        # instead of re-assembling the rows — and skips its own
        # draw_negatives pass.
        self.n_neg = n_neg
        if n_neg > 0:
            self.tgt = np.zeros((depth, chunk, 1 + n_neg), np.int32)
            self.ctx = self.tgt[..., 0]
            self.neg = self.tgt[..., 1:]
        else:
            self.tgt = None
            self.ctx = np.zeros((depth, chunk), np.int32)
            self.neg = None
        self.nv = np.zeros(depth, np.int32)
        self.lrs = np.zeros(depth, np.float32)
        self.d = 0          # chunks filled
        self.fill = 0       # rows filled in the current chunk
        # ``sink``: where sealed superchunks go. Default = dispatch the
        # device step inline (serial). The overlapped fit loop passes a
        # queue.put so a producer thread can run ALL host work (pair
        # gen + negative draws, everything rng-ordered) while the main
        # thread drains device dispatches (VERDICT r4 #2).
        self.sink = sink if sink is not None else self.m._dispatch_chunks
        if model.use_hs:
            model._ensure_hs_matrices()

    def push(self, centers: np.ndarray, contexts: np.ndarray,
             tokens: float = 0.0, negs: np.ndarray = None):
        """``tokens`` spreads that many corpus tokens' worth of
        lr-anneal progress evenly over these pairs, so producers that
        batch many sequences per push (the round-4 slab path) keep the
        same smooth decay the per-sequence producer had — advancing
        ``seen`` up front would snap small corpora straight to
        min_learning_rate (code-review r4). ``negs``: per-pair
        (n, n_neg) fused negative draws (requires n_neg at
        construction)."""
        if len(centers) == 0:
            self.seen += tokens
            return
        per = tokens / len(centers)
        p = 0
        while p < len(centers):
            take = min(self.chunk - self.fill, len(centers) - p)
            self.seen += per * take
            self.cen[self.d, self.fill:self.fill + take] = \
                centers[p:p + take]
            self.ctx[self.d, self.fill:self.fill + take] = \
                contexts[p:p + take]
            if negs is not None:
                self.neg[self.d, self.fill:self.fill + take] = \
                    negs[p:p + take]
            self.fill += take
            p += take
            if self.fill == self.chunk:
                self._seal_chunk()

    def _seal_chunk(self):
        self.nv[self.d] = self.fill
        self.lrs[self.d] = self.m._lr(self.seen, self.total)
        self.d += 1
        self.fill = 0
        if self.d == self.depth:
            self._flush()

    def finish(self):
        if self.fill:
            self._seal_chunk()
        self._flush()

    def _flush(self):
        """Seal the superchunk: finish ALL host-side work (including the
        rng-ordered negative draws, so producer-thread and serial modes
        make identical rng calls in identical order → bitwise-equal
        training) and hand the prepared arrays to the sink."""
        if self.d == 0:
            return
        m = self.m
        self.nv[self.d:] = 0                 # unused chunks are inert
        self.lrs[self.d:] = 0.0
        if m.use_hs:
            prep = ("hs", self.cen.copy(), self.ctx.copy(),
                    self.nv.copy(), self.lrs.copy())
        elif getattr(m, "shared_negatives", False) and m.negative > 0 \
                and self.chunk % sk.SHARED_NEG_GROUP == 0:
            g = self.chunk // sk.SHARED_NEG_GROUP
            draws = m._rng.integers(0, len(m._table),
                                    (self.depth, g, m.negative))
            negs = m._table[draws].astype(np.int32)
            prep = ("shared", self.cen.copy(), self.ctx.copy(),
                    self.nv.copy(), self.lrs.copy(), negs)
        else:
            k = 1 + m.negative
            if self.n_neg:
                # fused producers already drew per-pair negatives on
                # their counter streams and pushed them interleaved
                # into self.tgt; rows past nv are inert (stale but
                # always-valid indices under the nv mask)
                tgt = self.tgt.copy()
            else:
                tgt = np.zeros((self.depth, self.chunk, k), np.int32)
                tgt[..., 0] = self.ctx
                flat = tgt.reshape(-1, k)
                flat[:, 1:] = sk.draw_negatives(
                    m._rng, m._table, flat[:, 0:1], k - 1,
                    m.vocab.num_words())
            prep = ("perpair", self.cen.copy(), tgt,
                    self.nv.copy(), self.lrs.copy())
        self.d = 0
        self.sink(prep)


class SequenceVectors:
    """Builder-configured embedding trainer (reference:
    SequenceVectors.Builder)."""

    def __init__(self,
                 layer_size: int = 100,
                 window_size: int = 5,
                 min_word_frequency: int = 1,
                 iterations: int = 1,
                 epochs: int = 1,
                 negative: int = 5,
                 use_hierarchic_softmax: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 sampling: float = 0.0,
                 batch_size: int = 512,
                 seed: int = 42,
                 stop_words: Iterable[str] = (),
                 use_cbow: bool = False,
                 device_pair_generation: bool = False,
                 shared_negatives: bool = True,
                 overlap_pairgen: bool = True,
                 pairgen: str = "auto"):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.epochs = epochs
        self.negative = negative if not use_hierarchic_softmax else 0
        self.use_hs = use_hierarchic_softmax
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.sampling = sampling
        self.batch_size = batch_size
        self.seed = seed
        self.stop_words = stop_words
        self.use_cbow = use_cbow
        # opt-in: generate (center, context) pairs ON DEVICE
        # (skipgram_token_step). Removes the host pair pipeline entirely
        # — the right trade when host CPU is contended — but the batched
        # clip pass costs more device time per pair, so the tuned host
        # pair path measures faster on a dedicated host (101-119k vs
        # ~76k tokens/s at 100k vocab); hence not the default.
        self.device_pair_generation = device_pair_generation
        # Negative samples shared per 512-pair group (skipgram.py
        # _sg_update_shared): the exact per-pair draw is gather-latency
        # bound on TPU; sharing turns negative work into MXU matmuls
        # (measured ~3× SGNS throughput). Same negative DISTRIBUTION,
        # different per-pair draws; False restores per-pair negatives.
        self.shared_negatives = shared_negatives
        # Double-buffer host pair generation against device compute
        # (VERDICT r4 #2): a producer thread prepares superchunk N+1
        # while the device trains on N. Identical math (same rng call
        # order); False restores the strictly serial loop.
        self.overlap_pairgen = overlap_pairgen
        # Host pair-generation backend (PERF r11 / ROADMAP #3):
        #   "auto"   — the fused subsample+walk+negatives pass
        #              (native/dl4j_native.cpp when built, else its
        #              bitwise-identical numpy fallback)
        #   "numpy"  — the fused pass, fallback pinned (the A/B bench's
        #              reference arm)
        #   "legacy" — the r6 separate-stage numpy producer
        # The fused backends own a counter-based splitmix64 stream
        # seeded off ``seed`` (nlp/pairgen.py), so they are seeded-
        # reproducible but not pair-for-pair identical to "legacy".
        if pairgen not in ("auto", "numpy", "legacy"):
            raise ValueError(f"pairgen must be auto|numpy|legacy, "
                             f"got {pairgen!r}")
        self.pairgen = pairgen

        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[jax.Array] = None
        self.syn1: Optional[jax.Array] = None
        self._rng = np.random.default_rng(seed)
        self._table: Optional[np.ndarray] = None
        self._max_code_len = 0

    # ---- vocab + tables --------------------------------------------------
    def build_vocab(self, sequences: Iterable[Sequence[str]],
                    special_tokens: Iterable[str] = ()):
        ctor = VocabConstructor(self.min_word_frequency, self.stop_words)
        self.vocab = ctor.build_vocab(
            (list(s) for s in sequences), special_tokens=special_tokens)
        if self.use_hs:
            Huffman(self.vocab.vocab_words()).build()
            self._max_code_len = max(
                (len(w.codes) for w in self.vocab.vocab_words()), default=1)
        return self

    def _init_tables(self):
        n, d = self.vocab.num_words(), self.layer_size
        rng = np.random.default_rng(self.seed)
        syn0 = ((rng.random((n, d)) - 0.5) / d).astype(np.float32)
        rows1 = max(n - 1, 1) if self.use_hs else n
        # jnp.array, NOT jnp.asarray: the CPU backend zero-copy ADOPTS
        # numpy buffers, and the training kernels DONATE syn0/syn1 — a
        # donated adopted buffer is freed by numpy when the temp dies
        # while the donation chain still lives there (use-after-free:
        # syn0 reads back garbage/NaN at GC-dependent times). Any array
        # entering a donated argument chain must own its buffer.
        self.syn0 = jnp.array(syn0)
        self.syn1 = jnp.zeros((rows1, d), jnp.float32)
        if not self.use_hs:
            self._table = self.vocab.unigram_table()
        else:
            self._ensure_hs_matrices()

    def _ensure_hs_matrices(self):
        """Device-resident Huffman-path matrices for the vectorized HS
        step (host loop ships only index pairs). Built lazily so models
        whose tables arrived WITHOUT _init_tables — deserialized models,
        DistributedWord2Vec workers — still fast-path correctly."""
        if getattr(self, "_hs_points", None) is not None:
            return
        if not self._max_code_len:
            self._max_code_len = max(
                (len(w.codes) for w in self.vocab.vocab_words()),
                default=1)
        pts, labs, hmask = sk.build_hs_matrices(
            self.vocab.vocab_words(), max(self._max_code_len, 1))
        self._hs_points = jnp.asarray(pts)
        self._hs_labels = jnp.asarray(labs)
        self._hs_mask = jnp.asarray(hmask)

    # ---- training --------------------------------------------------------
    def fit(self, sequences: Iterable[Sequence[str]]):
        if isinstance(sequences, list) and all(
                isinstance(s, list) for s in sequences):
            seqs = sequences   # host pairgen is the SGNS bound: don't
        else:                  # re-copy an already-materialized corpus
            seqs = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seqs)
        if self.syn0 is None:
            self._init_tables()
        total_words = max(
            1, sum(len(s) for s in seqs) * self.epochs * self.iterations)
        if (self.use_cbow and self._fast_hooks_ok()
                and hasattr(self, "_fit_fast_cbow")):
            return self._fit_fast_cbow(seqs, total_words)
        if self._fast_sgns_ok():
            if self.device_pair_generation:
                if (not self.use_hs and self.sampling == 0.0
                        and self.negative > 0):
                    return self._fit_tokens_sgns(seqs, total_words)
                import warnings
                warnings.warn(
                    "device_pair_generation only covers plain SGNS "
                    "(negative>0, sampling=0, no HS/CBOW); falling back "
                    "to the host pair pipeline", stacklevel=2)
            return self._fit_fast_sgns(seqs, total_words)
        k = self._k()
        batcher = sk.PairBatcher(self.batch_size, k)
        seen = 0
        for _epoch in range(self.epochs):
            for seq in seqs:
                idxs = self._indices(seq)
                for _it in range(self.iterations):
                    seen = self._train_sequence(
                        idxs, batcher, seen, total_words)
        self._flush(batcher, self._lr(seen, total_words))
        return self

    # ---- vectorized SGNS hot path ---------------------------------------
    def _fast_sgns_ok(self) -> bool:
        """The vectorized path covers plain skip-gram negative sampling.
        Word2Vec's overrides delegate here for non-CBOW, so it qualifies;
        ParagraphVectors/GloVe run their own fit loops and never reach
        this. Subclasses that customize pair generation must override
        ``_add_pair`` or ``_train_sequence`` — either disqualifies them
        automatically (the slow path's per-sequence hook is
        ``_train_sequence``, so a subclass overriding only that must not
        silently get generic SGNS behavior). A subclass whose override
        merely delegates (Word2Vec) can opt back in by setting
        ``_sgns_fast_path_safe = True`` on the override function."""
        return (not self.use_cbow and self._fast_hooks_ok())

    def _fast_hooks_ok(self) -> bool:
        """True when no subclass customizes pair generation (the
        condition for ANY vectorized path — SGNS, HS, or CBOW)."""
        ts = type(self)._train_sequence
        train_seq_ok = (ts is SequenceVectors._train_sequence
                        or getattr(ts, "_sgns_fast_path_safe", False))
        return (self.iterations == 1
                and type(self)._add_pair is SequenceVectors._add_pair
                and train_seq_ok)

    def _fit_tokens_sgns(self, seqs, total_words: int):
        """Device-side pair generation (skipgram_token_step): the host
        ships padded (S, L) token-id matrices; window expansion,
        negative sampling, and the update all run in one jitted step.
        Used for plain SGNS without subsampling — the host pair pipeline
        caps at ~120k tokens/s, this path removes it entirely.

        Sentences longer than the row width are chunked and windows do
        not cross chunk boundaries — the same truncation word2vec.c
        applies at MAX_SENTENCE_LENGTH (its sentences split at 1000
        tokens); with L<=512 the lost boundary pairs are <=W(W+1) per
        chunk."""
        W = self.window_size
        # row width: fit the longest sentence piece (cap 512) — padding
        # slots still compute masked pairs, so loose rows burn device
        # time (40-token sentences in 128-wide rows = 3x waste)
        max_len = max((len(s) for s in seqs), default=2)
        L = int(min(512, max(8, max_len)))
        rows_per_epoch = sum((len(s) + L - 1) // L for s in seqs) or 1
        est_rows = rows_per_epoch * self.epochs
        # flush sizing: ~256k pair slots amortizes dispatch overhead
        # without blowing up the clip's sort/cumsum working set; shrink
        # for small corpora so they still get >=~64 optimizer steps
        budget_rows = max(4, 262144 // (L * 2 * W))
        S = int(np.clip(est_rows // 64, 4, budget_rows))
        buf = np.zeros((S, L), np.int32)
        lens = np.zeros(S, np.int32)
        # host table -> device, once per fit
        table_dev = jnp.asarray(np.asarray(  # host-sync-ok: one-time
            self._table, np.int32))
        key = jax.random.PRNGKey(self.seed ^ 0x5EED)
        fill = 0
        seen = 0
        n_flush = 0

        def flush(n):
            nonlocal fill, n_flush
            if n == 0:
                return
            if n < S:
                lens[n:] = 0
            lr = self._lr(seen, total_words)
            self.syn0, self.syn1 = sk.skipgram_token_step(
                # .copy(): the host loop mutates these buffers while
                # the async transfer may still be reading them — shipping
                # the live buffer races and corrupts batches
                self.syn0, self.syn1, jnp.asarray(buf.copy()),
                jnp.asarray(lens.copy()), table_dev,
                jax.random.fold_in(key, n_flush), jnp.float32(lr),
                window=W, n_neg=self.negative)
            n_flush += 1
            fill = 0

        for _epoch in range(self.epochs):
            for seq in seqs:
                idxs = np.asarray(  # host-sync-ok: host token encode
                    self._indices(seq), np.int32)
                seen += len(idxs)
                for lo in range(0, len(idxs), L):
                    piece = idxs[lo:lo + L]
                    if len(piece) < 2:
                        continue
                    buf[fill, :len(piece)] = piece
                    lens[fill] = len(piece)
                    fill += 1
                    if fill == S:
                        flush(S)
        flush(fill)
        return self

    def _dispatch_chunks(self, prep):
        """Run one prepared superchunk as a scanned device step. Pure
        consumer: all host randomness already happened in _PairStream.
        JAX dispatch is async, so successive calls pipeline on device."""
        kind = prep[0]
        if kind == "hs":
            _, cen, ctx, nv, lrs = prep
            self.syn0, self.syn1 = sk.skipgram_hs_scan_step(
                self.syn0, self.syn1, jnp.asarray(cen), jnp.asarray(ctx),
                self._hs_points, self._hs_labels, self._hs_mask,
                jnp.asarray(nv), jnp.asarray(lrs))
        elif kind == "shared":
            _, cen, ctx, nv, lrs, negs = prep
            self.syn0, self.syn1 = sk.skipgram_scan_step_shared(
                self.syn0, self.syn1, jnp.asarray(cen), jnp.asarray(ctx),
                jnp.asarray(negs), jnp.asarray(nv), jnp.asarray(lrs))
        else:
            _, cen, tgt, nv, lrs = prep
            self.syn0, self.syn1 = sk.skipgram_scan_step(
                self.syn0, self.syn1, jnp.asarray(cen), jnp.asarray(tgt),
                jnp.asarray(nv), jnp.asarray(lrs))

    def _run_overlapped(self, produce, queue_depth: int = 2):
        """Double-buffered fit loop (VERDICT r4 #2 — the reference
        overlaps via trainer threads, SequenceVectors.java:193): a
        producer thread runs ``produce(sink)`` — all host pair
        generation, numpy slab ops release the GIL — pushing prepared
        superchunks into a bounded queue while this thread drains
        device dispatches. Bitwise-identical to the serial path: the
        producer makes the same rng calls in the same order, and
        dispatch order is FIFO."""
        import queue as _queue
        import threading

        q: "_queue.Queue" = _queue.Queue(maxsize=queue_depth)
        done = object()
        stop = threading.Event()

        class _Stop(BaseException):
            pass

        def sink(prep):
            if stop.is_set():       # consumer died: end pairgen NOW,
                raise _Stop()       # not after the remaining corpus
            q.put(prep)

        def producer():
            try:
                produce(sink)
                q.put(done)
            except _Stop:
                q.put(done)
            except BaseException as e:          # surface in consumer
                q.put(e)

        t = threading.Thread(target=producer, daemon=True,
                             name="dl4j-pairgen")
        t.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    break
                if isinstance(item, BaseException):
                    raise item
                self._dispatch_chunks(item)
        finally:
            # consumer died mid-stream: signal the producer (it aborts
            # at its next sealed superchunk) and drain until its
            # terminal token so a q.put can't deadlock against join()
            stop.set()
            while t.is_alive():
                try:
                    item = q.get(timeout=0.1)
                except _queue.Empty:
                    continue
                if item is done or isinstance(item, BaseException):
                    break
            t.join()

    def _pair_chunk_size(self, est_pairs: int) -> int:
        """Chunk sizing shared by the vectorized pair paths: large chunks
        amortize per-dispatch latency (~26 ms over tunneled transports —
        PERF_ANALYSIS.md); update staleness within a chunk is the same
        hogwild-style race the reference's multithreaded native loop
        accepts (SURVEY §3.6). Scaled to the corpus so small corpora
        still get ≥~64 sequential optimizer steps per fit. Rounded up
        to the shared-negative group size so the grouped kernel's
        [G, group] reshape always divides."""
        c = int(np.clip(est_pairs // 64, self.batch_size, 65536))
        g = sk.SHARED_NEG_GROUP
        return -(-c // g) * g

    def _encode_corpus_flat(self, seqs):
        """One host pass over the corpus: vocab lookup into a flat int32
        id array plus the sequence id of every surviving token. Round 4:
        the per-sequence ``_indices`` loop was the measured host bound
        of the SGNS path (75k tiny numpy calls at the 100k-vocab
        bench); everything downstream is corpus-level numpy."""
        import itertools
        lookup = self.vocab._by_word
        lens = np.fromiter((len(s) for s in seqs), np.int64, len(seqs))
        total = int(lens.sum())
        # stream the corpus through map(dict.get, tokens, repeat(-1))
        # — an index dict keeps the whole lookup in C (map feeds get's
        # default from the second iterable), where the previous
        # ``vw.index if vw is not None`` genexpr ran a Python-level
        # branch per token (~1.1 s of the 3 s DBOW producer at the
        # 2M-token bench). Cached on the vocab object: lookup dicts
        # outlive fits, rebuilds swap the vocab instance.
        by_idx = getattr(self.vocab, "_index_by_word", None)
        if by_idx is None:
            by_idx = {w: vw.index for w, vw in lookup.items()}
            self.vocab._index_by_word = by_idx
        idx = np.fromiter(
            map(by_idx.get, itertools.chain.from_iterable(seqs),
                itertools.repeat(-1)), np.int32, total)
        keep = idx >= 0
        seq_id = np.repeat(np.arange(len(seqs)), lens)[keep]
        return idx[keep], seq_id

    def _subsample_mask(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized frequent-word subsampling (word2vec.c's keep
        probability), redrawn per epoch like the sequential path. The
        per-index counts array is cached — vocab counts are fixed for
        the whole fit (code-review r4)."""
        cached = getattr(self, "_counts_arr", None)
        # keyed on vocab object identity: a rebuilt vocab of equal SIZE
        # must not reuse stale frequencies (code-review r4)
        if cached is None or cached[0] is not self.vocab:
            counts = np.zeros(self.vocab.num_words(), np.float64)
            for vw in self.vocab.vocab_words():
                counts[vw.index] = vw.count
            self._counts_arr = cached = (self.vocab, counts)
        counts = cached[1]
        total = max(1, self.vocab.total_word_count)
        f = counts[ids] / total
        keep_p = (np.sqrt(f / self.sampling) + 1) * self.sampling \
            / np.maximum(f, 1e-300)
        return self._rng.random(len(ids)) < keep_p

    def _window_slabs(self, ids_all, seq_all, slab: int = 1 << 20,
                      extras=None):
        """The ONE corpus-level randomized-window walk (word2vec.c's
        ``b`` per center): per epoch — subsample, per-token positions,
        effective windows — then ~1M-token slabs, each yielding
        ``(ids, lo, hi, grid, valid)`` where ``grid`` is the clipped
        (slab, 2W) context-position grid and ``valid`` its mask. An
        epoch too short to window yields ``(ids, 0, n, None, None)``
        (token progress only). SGNS flattens the valid cells into
        pairs; CBOW consumes the rows whole — one implementation, one
        anneal-accounting contract.

        ``extras``: optional tuple of per-token corpus-level arrays
        (same length as ``ids_all``) that must ride along through the
        per-epoch subsample filter — e.g. DBOW's per-token label rows.
        When given, each yield grows a sixth element: the tuple of
        ``[lo:hi]`` slab slices of the filtered extras."""
        W = self.window_size
        offsets = np.concatenate([np.arange(-W, 0),
                                  np.arange(1, W + 1)]).astype(np.int32)
        abs_off = np.abs(offsets)[None, :]
        for _epoch in range(self.epochs):
            if self.sampling > 0:
                m = self._subsample_mask(ids_all)
                ids = ids_all[m]
                seq_id = seq_all[m]
                ex = (tuple(e[m] for e in extras)
                      if extras is not None else None)
            else:
                ids, seq_id = ids_all, seq_all
                ex = extras
            n = len(ids)
            if n < 2:
                if extras is not None:
                    yield ids, 0, n, None, None, ex
                else:
                    yield ids, 0, n, None, None
                continue
            pos, length = _corpus_positions(seq_id)
            # randomized effective window per center (word2vec.c's b)
            w_eff = (self._rng.integers(1, W + 1, size=n).astype(np.int32)
                     if W > 1 else np.ones(n, np.int32))
            for lo in range(0, n, slab):
                hi = min(n, lo + slab)
                o = offsets[None, :]
                po = pos[lo:hi, None] + o
                valid = ((abs_off <= w_eff[lo:hi, None])
                         & (po >= 0)
                         & (po < length[lo:hi, None]))
                grid = np.arange(lo, hi, dtype=np.int32)[:, None] + o
                np.clip(grid, 0, n - 1, out=grid)
                if extras is not None:
                    yield (ids, lo, hi, grid, valid,
                           tuple(e[lo:hi] for e in ex))
                else:
                    yield ids, lo, hi, grid, valid

    def _fused_n_neg(self, chunk: int) -> int:
        """Per-pair negative count the FUSED producers draw on their
        counter streams — 0 when the flush-time path owns negatives
        (HS has none; the shared-negatives mode keeps its grouped
        ``_rng`` draws, which turn negative work into MXU matmuls)."""
        if self.use_hs or self.negative <= 0:
            return 0
        if getattr(self, "shared_negatives", False) \
                and chunk % sk.SHARED_NEG_GROUP == 0:
            return 0
        return self.negative

    def _fit_fast_sgns(self, seqs, total_words: int):
        """Whole-corpus vectorized skip-gram (negative sampling OR
        hierarchical softmax): ONE vocab-lookup pass flattens the corpus
        (``_encode_corpus_flat``), then pair generation runs as
        corpus-level numpy over an offsets grid in ~1M-token slabs —
        no per-sequence Python (``_window_slabs``). Negatives are one
        table gather per chunk, Huffman paths are gathered on device
        from precomputed matrices; each superchunk is a single donated
        scanned device step — the TPU-shaped version of the reference's
        AggregateSkipGram batching (SkipGram.java:176-186).

        ``pairgen != "legacy"`` swaps the producer for the fused
        subsample+walk+negatives pass (nlp/pairgen.py, native when
        built) — same _PairStream consumer, same anneal accounting."""
        W = self.window_size
        chunk = self._pair_chunk_size(total_words * (W + 1))
        ids_all, seq_all = self._encode_corpus_flat(seqs)

        if self.pairgen != "legacy":
            from deeplearning4j_tpu.nlp import pairgen as pg
            walker = pg.CorpusWalker(
                self, ids_all, seq_all,
                force_numpy=self.pairgen == "numpy")
            n_neg = self._fused_n_neg(chunk)

            def produce(sink):
                stream = _PairStream(self, chunk, total_words,
                                     sink=sink, n_neg=n_neg)
                for ep in range(self.epochs):
                    view = walker.epoch(ep)
                    if view.n < 2:
                        stream.seen += view.n
                        continue
                    pair_base = 0       # NEG streams are per-epoch
                    for lo, hi in view.slab_bounds():
                        c, x, negs = view.walk(lo, hi, n_neg=n_neg,
                                               pair_base=pair_base)
                        pair_base += len(c)
                        stream.push(c, x, tokens=hi - lo, negs=negs)
                stream.finish()
        else:
            def produce(sink):
                stream = _PairStream(self, chunk, total_words, sink=sink)
                for ids, lo, hi, grid, valid in self._window_slabs(
                        ids_all, seq_all):
                    if valid is None:
                        stream.seen += hi - lo
                        continue
                    centers = np.repeat(ids[lo:hi], valid.sum(axis=1))
                    stream.push(centers, ids[grid[valid]],
                                tokens=hi - lo)
                stream.finish()

        if self.overlap_pairgen:
            self._run_overlapped(produce)
        else:
            produce(None)      # _PairStream defaults to inline dispatch
        return self

    def _k(self) -> int:
        return (self._max_code_len if self.use_hs else 1 + self.negative)

    def _lr(self, seen: int, total: int) -> float:
        frac = min(1.0, seen / total)
        return max(self.min_learning_rate,
                   self.learning_rate * (1.0 - frac))

    def _indices(self, seq: Sequence[str]) -> List[int]:
        """Vocab lookup + frequent-word subsampling (word2vec.c style;
        reference applies sampling in SequenceVectors' transformer)."""
        lookup = self.vocab._by_word
        if self.sampling <= 0:
            # host pair generation feeds a device that now sustains
            # >500k tokens/s — this per-token loop IS the hot path, so
            # one dict-hit comprehension, no per-token method calls
            return [vw.index for vw in map(lookup.get, seq)
                    if vw is not None]
        out = []
        total = max(1, self.vocab.total_word_count)
        for tok in seq:
            vw = lookup.get(tok)
            if vw is None:
                continue
            f = vw.count / total
            keep = (np.sqrt(f / self.sampling) + 1) * self.sampling / f
            if self._rng.random() > keep:
                continue
            out.append(vw.index)
        return out

    def _window_bounds(self, pos: int, n: int) -> Tuple[int, int]:
        """Randomized effective window (word2vec.c's ``b = rng % window``):
        the one shared implementation for SkipGram/CBOW/DM paths."""
        window = self.window_size
        b = int(self._rng.integers(window)) if window > 1 else 0
        return (max(0, pos - (window - b)),
                min(n, pos + (window - b) + 1))

    def _train_sequence(self, idxs: List[int], batcher: sk.PairBatcher,
                        seen: int, total: int) -> int:
        for pos, center in enumerate(idxs):
            lo, hi = self._window_bounds(pos, len(idxs))
            for cpos in range(lo, hi):
                if cpos == pos:
                    continue
                self._add_pair(center, idxs[cpos], batcher, seen, total)
            seen += 1
        return seen

    def _add_pair(self, center: int, context: int, batcher: sk.PairBatcher,
                  seen: int, total: int):
        """SkipGram: center predicts context → (row=center, target=context).
        word2vec.c trains syn0[context] against syn1[center-path]; either
        orientation is symmetric over the corpus."""
        if self.use_hs:
            targets, labels = sk.hs_targets(
                self.vocab.element_at_index(context))
        else:
            targets, labels = sk.negative_sample_targets(
                context, self._table, self.negative, self._rng)
        if batcher.add(center, targets, labels):
            self._flush(batcher, self._lr(seen, total))

    def _flush(self, batcher: sk.PairBatcher, lr: float):
        if batcher.n == 0 and batcher.mask.sum() == 0:
            return
        centers, targets, labels, mask, _n = batcher.take()
        self.syn0, self.syn1 = sk.skipgram_step(
            self.syn0, self.syn1, jnp.asarray(centers), jnp.asarray(targets),
            jnp.asarray(labels), jnp.asarray(mask),
            jnp.float32(lr))

    # ---- lookup API (reference: WordVectors interface) -------------------
    @property
    def word_vectors_matrix(self) -> np.ndarray:
        return np.asarray(self.syn0)  # host-sync-ok: user-facing egress

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def get_word_vector(self, word: str) -> np.ndarray:
        idx = self.vocab.index_of(word)
        if idx < 0:
            raise KeyError(word)
        return np.asarray(self.syn0[idx])  # host-sync-ok: user egress

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        na, nb = np.linalg.norm(va), np.linalg.norm(vb)
        if na == 0 or nb == 0:
            return 0.0
        return float(va @ vb / (na * nb))  # host-sync-ok: host numpy

    def words_nearest(self, word, top_n: int = 10) -> List[str]:
        """Cosine top-k on device (reference: wordsNearest via
        BasicModelUtils; here one matmul on the MXU)."""
        if isinstance(word, str):
            v = jnp.asarray(self.get_word_vector(word))
            exclude = {self.vocab.index_of(word)}
        else:
            v = jnp.asarray(np.asarray(  # host-sync-ok: caller vec
                word, np.float32))
            exclude = set()
        m = self.syn0 / jnp.maximum(
            jnp.linalg.norm(self.syn0, axis=1, keepdims=True), 1e-9)
        sims = m @ (v / jnp.maximum(jnp.linalg.norm(v), 1e-9))
        order = np.asarray(  # host-sync-ok: user-facing top-k egress
            jnp.argsort(-sims))
        out = []
        for idx in order:
            if int(idx) in exclude:
                continue
            out.append(self.vocab.word_at_index(int(idx)))
            if len(out) >= top_n:
                break
        return out

    def words_nearest_sum(self, positive: List[str], negative: List[str],
                          top_n: int = 10) -> List[str]:
        v = sum(self.get_word_vector(w) for w in positive)
        for w in negative:
            v = v - self.get_word_vector(w)
        out = self.words_nearest(v, top_n + len(positive) + len(negative))
        skip = set(positive) | set(negative)
        return [w for w in out if w not in skip][:top_n]
