"""Batched SkipGram / CBOW device kernels.

TPU-native replacement for the reference's native aggregate ops: the
reference batches (center, context) pairs into ``AggregateSkipGram`` /
``AggregateCBOW`` and executes them in C++ via
``Nd4j.getExecutioner().exec(batches)``
(models/embeddings/learning/impl/elements/SkipGram.java:176,271; CBOW.java).

Here the same batching idea becomes ONE jitted step per batch: gather the
center rows from syn0 and the target rows (negative samples or Huffman
inner nodes) from syn1, compute the sigmoid-gradient for every pair at
once on the MXU, and scatter the updates back. Buffers are donated so
the embedding tables are updated in place on device.

Duplicate rows in a batch scatter-add as usual but each row's TOTAL
accumulated update is norm-clipped: word2vec's sequential (hogwild)
updates are self-limiting — each saturating step sees the previous one's
result — but a batched scatter-add applies k duplicate updates computed
from the SAME pre-update row. For frequent words (or tiny vocabularies)
k is large; once row norms grow, the summed step overshoots and the
feedback loop diverges to overflow as batch size grows. Clipping the
per-row accumulated update norm (at 1.0 — well above any healthy
per-batch step, far below the runaway regime) bounds the feedback loop
at any batch size — which the dispatch-overhead economics push toward
64k+ (PERF_ANALYSIS.md). This is a deliberate, small semantic deviation
from word2vec.c's sequential updates: sub-threshold batches differ from
a sequential replay only by float summation order, and a frequent word
whose legitimate accumulated update exceeds the threshold takes a
direction-preserving, norm-1 step instead (word2vec.c, applying the
same pairs one at a time through a saturating sigmoid, also never moves
a row by more than O(1) per batch — the clip restores that property,
it does not add a new one).

The clip works on the B·K update rows directly (sort by index +
segment sums), NOT by materializing a dense [V, D] accumulator — per
step cost stays O(B·K·D + B·K log B·K) regardless of vocab size.

The math (per pair, label y ∈ {0,1}, lr α):
    g = (y − σ(syn0[c]·syn1[t])) · α
    syn1[t] += g · syn0[c]
    syn0[c] += g · syn1[t]        (pre-update value, as in word2vec.c)
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _sg_update(syn0: jax.Array, syn1: jax.Array,
               centers: jax.Array,      # [B] int32
               targets: jax.Array,      # [B, K] int32
               labels: jax.Array,       # [B, K] float32 (1=pos, 0=neg)
               mask: jax.Array,         # [B, K] float32
               lr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One batched SkipGram update (negative sampling or hierarchical
    softmax — identical math, different targets/labels)."""
    h = syn0[centers]                                  # [B, D]
    w = syn1[targets]                                  # [B, K, D]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (labels - jax.nn.sigmoid(logits)) * mask * lr  # [B, K]
    dh = jnp.einsum("bk,bkd->bd", g, w)                # grad wrt syn0 rows
    dw = g[..., None] * h[:, None, :]                  # [B, K, D]
    d = syn0.shape[1]
    mr = _max_row_norm(lr, d)
    syn1 = _clipped_scatter(syn1, targets.reshape(-1), dw.reshape(-1, d),
                            mr)
    syn0 = _clipped_scatter(syn0, centers, dh, mr)
    return syn0, syn1


skipgram_step = functools.partial(jax.jit, donate_argnums=(0, 1))(
    _sg_update)


# Divergence-guard clip, scaled with lr and layer size: at word2vec.c
# defaults (lr=0.025, D=100) this reproduces the old absolute threshold
# of 1.0, but high-lr or large-D configs no longer have legitimate
# per-chunk updates silently clipped (advisor r2).
_CLIP_COEF = 4.0


def _max_row_norm(lr: jax.Array, d: int) -> jax.Array:
    return _CLIP_COEF * lr * jnp.sqrt(jnp.float32(d))


def _clipped_scatter(table: jax.Array, idx: jax.Array,
                     upd: jax.Array, max_norm: jax.Array) -> jax.Array:
    """table[idx] += updates, with each destination row's accumulated
    update norm-clipped (see module docstring). Segment-sum over the
    sorted update rows — no dense [V, D] temporaries, so cost scales
    with the batch, not the vocabulary.

    Every step here is duplicate-free by construction: segment bounds
    come from cummax/cummin over the sorted order (a scatter-max with
    duplicate indices lowers to a SERIAL per-element loop on TPU —
    profiled at ~48 ms per 64k-pair chunk, 50× the rest of the step),
    and the final scatter-add lands each segment total on its unique
    destination row while every other element targets its own slot in
    a dump area past the table, so XLA vectorizes the scatter AND the
    result stays bitwise deterministic (exactly one add per live row)."""
    b = idx.shape[0]
    order = jnp.argsort(idx)
    sid = jnp.take(idx, order)
    supd = jnp.take(upd, order, axis=0).astype(jnp.float32)
    pos = jnp.arange(b)
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    is_last = jnp.concatenate([sid[1:] != sid[:-1],
                               jnp.ones((1,), bool)])
    # ``total`` only has to be right at each segment's LAST element (all
    # other elements land in the dump area below), so the segment sum is
    # cs - cs[segment start - 1] evaluated elementwise: one cummax for
    # the start positions and ONE row gather — (b, D) gathers are the
    # dominant cost of this kernel on TPU
    seg_start = jax.lax.cummax(jnp.where(first, pos, -1))
    cs = jnp.cumsum(supd, axis=0)
    lo = jnp.where((seg_start > 0)[:, None],
                   jnp.take(cs, jnp.maximum(seg_start - 1, 0), axis=0),
                   0.0)
    total = cs - lo          # segment sum, valid at segment-last rows
    norm = jnp.linalg.norm(total, axis=-1, keepdims=True)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    nrows = table.shape[0]
    scatter_idx = jnp.where(is_last, sid, nrows + pos)
    padded = jnp.concatenate(
        [table, jnp.zeros((b,) + table.shape[1:], table.dtype)], axis=0)
    padded = padded.at[scatter_idx].add(
        (total * scale).astype(table.dtype), unique_indices=True)
    return padded[:nrows]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def skipgram_hs_step(syn0: jax.Array, syn1: jax.Array,
                     centers: jax.Array,      # [B] int32
                     contexts: jax.Array,     # [B] int32
                     points_mat: jax.Array,   # [V, L] int32 Huffman nodes
                     labels_mat: jax.Array,   # [V, L] float32 (1 - code)
                     hs_mask: jax.Array,      # [V, L] float32 path length
                     row_valid: jax.Array,    # [B] float32 batch padding
                     lr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Hierarchical-softmax SkipGram step with the Huffman-path gathers
    done ON DEVICE: targets/labels/mask come from per-word matrices, so
    the host loop ships only (center, context) index pairs — the same
    batching economics as the negative-sampling path."""
    targets = points_mat[contexts]                 # [B, L]
    labels = labels_mat[contexts]
    mask = hs_mask[contexts] * row_valid[:, None]
    return skipgram_step(syn0, syn1, centers, targets, labels, mask, lr)


def partial_mask(full_dev: jax.Array, n_valid: int) -> jax.Array:
    """All-ones device mask when the chunk is full; else a zero-padded
    host-built mask of the same shape — the one home for the padded-tail
    logic shared by every vectorized flush path."""
    shape = full_dev.shape
    if n_valid == shape[0]:
        return full_dev
    m = np.zeros(shape, np.float32)
    m[:n_valid] = 1.0
    return jnp.asarray(m)


def build_hs_matrices(vocab_words, max_len: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(points, labels=1-codes, mask) matrices padded to ``max_len`` for
    the device-side HS gather (rows indexed by word index)."""
    v = len(vocab_words)
    points = np.zeros((v, max_len), np.int32)
    labels = np.zeros((v, max_len), np.float32)
    mask = np.zeros((v, max_len), np.float32)
    for i, vw in enumerate(vocab_words):
        n = min(len(vw.points), max_len)
        points[i, :n] = vw.points[:n]
        labels[i, :n] = 1.0 - np.asarray(vw.codes[:n], np.float32)
        mask[i, :n] = 1.0
    return points, labels, mask


def _cbow_update(syn0: jax.Array, syn1: jax.Array,
                 context: jax.Array,       # [B, W] int32 context word rows
                 context_mask: jax.Array,  # [B, W] float32
                 targets: jax.Array,       # [B, K] int32
                 labels: jax.Array,        # [B, K] float32
                 mask: jax.Array,          # [B, K] float32
                 lr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One batched CBOW update: h = mean(context rows); the syn0 gradient
    is broadcast back to every context word (reference: CBOW.java via
    AggregateCBOW)."""
    cvecs = syn0[context]                               # [B, W, D]
    denom = jnp.maximum(context_mask.sum(-1, keepdims=True), 1.0)
    h = (cvecs * context_mask[..., None]).sum(1) / denom  # [B, D]
    w = syn1[targets]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (labels - jax.nn.sigmoid(logits)) * mask * lr
    dh = jnp.einsum("bk,bkd->bd", g, w) / denom          # [B, D]
    dw = g[..., None] * h[:, None, :]
    d = syn0.shape[1]
    mr = _max_row_norm(lr, d)
    syn1 = _clipped_scatter(syn1, targets.reshape(-1), dw.reshape(-1, d),
                            mr)
    dctx = (dh[:, None, :] * context_mask[..., None]).reshape(-1, d)
    syn0 = _clipped_scatter(syn0, context.reshape(-1), dctx, mr)
    return syn0, syn1


cbow_step = functools.partial(jax.jit, donate_argnums=(0, 1))(
    _cbow_update)


# ---- scanned multi-chunk steps -------------------------------------------
# One dispatch applies D sequential chunk updates via lax.scan: the
# per-dispatch transport overhead (~26 ms through the tunneled PJRT —
# PERF_ANALYSIS.md) is amortized D×, and the host builds the next
# superchunk while the device drains this one (async dispatch — the
# double-buffering the reference gets from its trainer threads feeding
# one fat native op per batch, SkipGram.java:176).

def _row_mask(b: int, k: int, nv: jax.Array) -> jax.Array:
    """(B, K) float mask of rows below the chunk's valid count."""
    return jnp.broadcast_to(
        (jnp.arange(b)[:, None] < nv).astype(jnp.float32), (b, k))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def skipgram_scan_step(syn0, syn1,
                       centers,   # [D, B] int32
                       targets,   # [D, B, K] int32 (col 0 = positive)
                       n_valid,   # [D] int32
                       lrs):      # [D] float32
    b, k = targets.shape[1], targets.shape[2]
    labels = jnp.zeros((b, k), jnp.float32).at[:, 0].set(1.0)

    def body(carry, chunk):
        s0, s1 = carry
        cen, tgt, nv, lr = chunk
        s0, s1 = _sg_update(s0, s1, cen, tgt, labels,
                            _row_mask(b, k, nv), lr)
        return (s0, s1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (centers, targets, n_valid, lrs))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def skipgram_hs_scan_step(syn0, syn1,
                          centers,     # [D, B] int32
                          contexts,    # [D, B] int32
                          points_mat, labels_mat, hs_mask,
                          n_valid, lrs):
    b = centers.shape[1]
    k = points_mat.shape[1]

    def body(carry, chunk):
        s0, s1 = carry
        cen, ctx, nv, lr = chunk
        targets = points_mat[ctx]
        labels = labels_mat[ctx]
        mask = hs_mask[ctx] * _row_mask(b, k, nv)
        s0, s1 = _sg_update(s0, s1, cen, targets, labels, mask, lr)
        return (s0, s1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (centers, contexts, n_valid, lrs))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_scan_step(syn0, syn1,
                   context,       # [D, B, W] int32
                   context_mask,  # [D, B, W] float32
                   targets,       # [D, B, K] int32 (col 0 = positive)
                   n_valid, lrs):
    b, k = targets.shape[1], targets.shape[2]
    labels = jnp.zeros((b, k), jnp.float32).at[:, 0].set(1.0)

    def body(carry, chunk):
        s0, s1 = carry
        ctx, cm, tgt, nv, lr = chunk
        s0, s1 = _cbow_update(s0, s1, ctx, cm, tgt, labels,
                              _row_mask(b, k, nv), lr)
        return (s0, s1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (context, context_mask, targets, n_valid,
                             lrs))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_hs_scan_step(syn0, syn1,
                      context,       # [D, B, W] int32
                      context_mask,  # [D, B, W] float32
                      centers,       # [D, B] int32
                      points_mat, labels_mat, hs_mask,
                      n_valid, lrs):
    b = centers.shape[1]
    k = points_mat.shape[1]

    def body(carry, chunk):
        s0, s1 = carry
        ctx, cm, cen, nv, lr = chunk
        targets = points_mat[cen]
        labels = labels_mat[cen]
        mask = hs_mask[cen] * _row_mask(b, k, nv)
        s0, s1 = _cbow_update(s0, s1, ctx, cm, targets, labels, mask,
                              lr)
        return (s0, s1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (context, context_mask, centers, n_valid,
                             lrs))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_hs_step(syn0: jax.Array, syn1: jax.Array,
                 context: jax.Array,       # [B, W] int32
                 context_mask: jax.Array,  # [B, W] float32
                 centers: jax.Array,       # [B] int32 (Huffman lookup)
                 points_mat: jax.Array,    # [V, L] int32
                 labels_mat: jax.Array,    # [V, L] float32
                 hs_mask: jax.Array,       # [V, L] float32
                 row_valid: jax.Array,     # [B] float32
                 lr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Hierarchical-softmax CBOW with the Huffman-path gather ON DEVICE
    (mirrors skipgram_hs_step): the host ships context ids + center ids
    only, instead of re-uploading gathered (B, L) target/label/mask
    arrays every chunk."""
    targets = points_mat[centers]
    labels = labels_mat[centers]
    mask = hs_mask[centers] * row_valid[:, None]
    return cbow_step(syn0, syn1, context, context_mask, targets, labels,
                     mask, lr)


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnames=("window", "n_neg"))
def skipgram_token_step(syn0: jax.Array, syn1: jax.Array,
                        tokens: jax.Array,    # (S, L) int32, padded
                        lengths: jax.Array,   # (S,) int32 valid lengths
                        table: jax.Array,     # unigram^0.75 table, int32
                        key: jax.Array, lr: jax.Array,
                        *, window: int, n_neg: int
                        ) -> Tuple[jax.Array, jax.Array]:
    """SGNS over raw token-id sentences with pair generation ON DEVICE.

    The host pipeline (word→id lookup aside) caps tokens/s at what numpy
    window expansion + negative gathers can produce (~120k tokens/s
    measured). Here the (center, context) grid, the per-center effective
    window draw (word2vec.c's ``b``), the negative samples, and the
    update all happen inside one jitted step: the host ships only padded
    int32 sentence matrices. Same math as skipgram_step (shared tail,
    incl. the clipped scatter); RNG is jax-side instead of host-side.
    """
    s, l = tokens.shape
    kb, kn = jax.random.split(key)
    pos = jnp.arange(l)
    offs = jnp.concatenate([jnp.arange(-window, 0),
                            jnp.arange(1, window + 1)])      # (2W,)
    b = jax.random.randint(kb, (s, l), 1, window + 1)
    grid = jnp.broadcast_to(pos[None, :, None] + offs[None, None, :],
                            (s, l, 2 * window))
    valid = ((jnp.abs(offs)[None, None, :] <= b[..., None])
             & (grid >= 0) & (grid < lengths[:, None, None])
             & (pos[None, :, None] < lengths[:, None, None]))
    centers = jnp.broadcast_to(tokens[:, :, None],
                               valid.shape).reshape(-1)
    ctx_idx = jnp.clip(grid, 0, l - 1)          # (S, L, 2W) positions
    contexts = jnp.take_along_axis(
        tokens, ctx_idx.reshape(s, -1), axis=1).reshape(-1)
    mask_row = valid.reshape(-1).astype(jnp.float32)

    p = centers.shape[0]
    negs = table[jax.random.randint(kn, (p, n_neg), 0, table.shape[0])]
    # a negative colliding with the positive would train the same target
    # toward both labels: cycle it (word2vec.c skips; same effect). The
    # vocab bound is syn1's static row count — free at trace time.
    vmax = max(syn1.shape[0], 2)
    negs = jnp.where(negs == contexts[:, None],
                     (negs + 1) % vmax, negs)
    targets = jnp.concatenate([contexts[:, None], negs], axis=1)
    labels = jnp.zeros((p, 1 + n_neg),
                       jnp.float32).at[:, 0].set(1.0)
    mask = jnp.broadcast_to(mask_row[:, None], (p, 1 + n_neg))
    return skipgram_step(syn0, syn1, centers, targets, labels, mask, lr)


@functools.partial(jax.jit, donate_argnums=(0,))
def infer_step(docvec: jax.Array,        # [D] the one trainable vector
               syn1: jax.Array,          # frozen
               targets: jax.Array,       # [P, K]
               labels: jax.Array,
               mask: jax.Array,
               lr: jax.Array) -> jax.Array:
    """ParagraphVectors.inferVector inner step: train a single new doc
    vector against a frozen syn1 (reference: ParagraphVectors.java
    inferVector)."""
    w = syn1[targets]                                   # [P, K, D]
    logits = jnp.einsum("d,pkd->pk", docvec, w)
    g = (labels - jax.nn.sigmoid(logits)) * mask * lr
    upd = jnp.einsum("pk,pkd->d", g, w).astype(jnp.float32)
    # the whole P*K pair sum lands on ONE row computed from the same
    # pre-update docvec — the worst case of the duplicate-sum divergence
    # _clipped_scatter guards against; clip it the same way
    norm = jnp.maximum(jnp.linalg.norm(upd), 1e-12)
    upd = upd * jnp.minimum(1.0, _max_row_norm(lr, docvec.shape[0]) / norm)
    return docvec + upd.astype(docvec.dtype)


class PairBatcher:
    """Host-side accumulator of (center, targets, labels) rows, flushed to
    the device kernel when full — the analog of the reference's batch list
    handed to the native executioner (SkipGram.java:176-186)."""

    def __init__(self, batch_size: int, k: int):
        self.batch_size = batch_size
        self.k = k
        self.centers = np.zeros(batch_size, np.int32)
        self.targets = np.zeros((batch_size, k), np.int32)
        self.labels = np.zeros((batch_size, k), np.float32)
        self.mask = np.zeros((batch_size, k), np.float32)
        self.n = 0

    def add(self, center: int, targets: np.ndarray, labels: np.ndarray):
        i = self.n
        kk = min(len(targets), self.k)
        self.centers[i] = center
        self.targets[i, :kk] = targets[:kk]
        self.labels[i, :kk] = labels[:kk]
        self.mask[i, :kk] = 1.0
        if kk < self.k:
            self.targets[i, kk:] = 0
            self.labels[i, kk:] = 0.0
            self.mask[i, kk:] = 0.0
        self.n += 1
        return self.n >= self.batch_size

    def take(self):
        out = (self.centers.copy(), self.targets.copy(),
               self.labels.copy(), self.mask.copy(), self.n)
        # zero masks beyond fill point so a partial flush is inert
        if self.n < self.batch_size:
            out[3][self.n:] = 0.0
        self.n = 0
        self.mask[:] = 0.0
        return out


def draw_negatives(rng: np.random.Generator, table: np.ndarray,
                   pos: np.ndarray, n_neg: int,
                   n_words: int) -> np.ndarray:
    """(n, n_neg) negatives from the unigram^0.75 table for positive
    column ``pos`` (n, 1): collisions with the positive are redrawn
    once, then cycled to (pos+1) mod vocab — the single home of the
    collision policy shared by the SGNS and CBOW fast paths."""
    n = pos.shape[0]
    # uint32 draws: ~2x faster than the int64 default in numpy's
    # Lemire path, and table indices always fit
    negs = table[rng.integers(0, len(table), (n, n_neg),
                              dtype=np.uint32)]
    bad = np.nonzero(negs == pos)
    if bad[0].size:
        # redraw/cycle only the colliding cells (~1 in vocab^0.25 of
        # pairs) — a second full-width compare cost more than all the
        # collisions combined at the 500k-pair chunk size
        redraw = table[rng.integers(0, len(table), bad[0].size)]
        pb = pos[bad[0], 0]
        still = redraw == pb
        redraw[still] = (pb[still] + 1) % max(n_words, 2)
        negs[bad] = redraw
    return negs


def window_grid(n: int, window: int, rng: np.random.Generator
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Randomized-effective-window offsets grid (word2vec.c's ``b``):
    returns (grid positions (n, 2W), validity mask (n, 2W)) shared by
    the SGNS and CBOW fast paths."""
    offsets = np.concatenate([np.arange(-window, 0),
                              np.arange(1, window + 1)])
    eff = (rng.integers(1, window + 1, n) if window > 1
           else np.ones(n, np.int64))
    grid = np.arange(n)[:, None] + offsets[None, :]
    valid = ((np.abs(offsets)[None, :] <= eff[:, None])
             & (grid >= 0) & (grid < n))
    return grid, valid


def negative_sample_targets(pos: int, table: np.ndarray, n_neg: int,
                            rng: np.random.Generator
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """1 positive + n_neg negatives drawn from the unigram^0.75 table.
    Negatives colliding with the positive are redrawn (word2vec.c skips
    target==word), so a row never trains the same target toward both
    labels at once."""
    negs = table[rng.integers(0, len(table), n_neg)]
    for _ in range(4):
        bad = negs == pos
        if not bad.any():
            break
        negs[bad] = table[rng.integers(0, len(table), int(bad.sum()))]
    if (negs == pos).any():  # tiny vocab: fall back to cycling indices
        n_words = int(table.max()) + 1
        negs[negs == pos] = (pos + 1) % max(n_words, 2)
    targets = np.concatenate(([pos], negs)).astype(np.int32)
    labels = np.zeros(1 + n_neg, np.float32)
    labels[0] = 1.0
    return targets, labels


def hs_targets(vw, max_len: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Hierarchical-softmax targets: Huffman inner nodes with label
    1−code (word2vec convention)."""
    points = np.asarray(vw.points, np.int32)
    labels = 1.0 - np.asarray(vw.codes, np.float32)
    if max_len is not None:
        points, labels = points[:max_len], labels[:max_len]
    return points, labels


# ---------------------------------------------------------------------------
# shared-negative-sample SkipGram (round 4)
# ---------------------------------------------------------------------------

SHARED_NEG_GROUP = 512


def _sg_update_shared(syn0, syn1,
                      centers,     # [B] int32
                      contexts,    # [B] int32
                      negs,        # [G, NEG] int32, B % G == 0
                      nv,          # scalar int32 valid rows
                      lr):
    """SkipGram update with PER-GROUP shared negative samples.

    Per-pair negative rows are the gather/scatter bound of the exact
    batched step (K+1 random ~512-byte row ops each way per pair —
    latency-, not bandwidth-, limited on TPU). Sharing one negative set
    across a group of ``B/G`` consecutive pairs turns the negative
    work into three batched MXU matmuls (logits, dh, dW) over [G,
    group, D] blocks, leaving only the positive context + center rows
    to gather/scatter. This is the published shared-negative-sampling
    batching (e.g. Ji et al., "Parallelizing Word2Vec in Shared and
    Distributed Memory", whose negative sharing this mirrors) — the
    negatives are i.i.d. draws either way; sharing them within a group
    changes which random negatives each pair sees, not their
    distribution. The reference's exact per-pair semantics remain
    available via shared_negatives=False.

    Negatives are drawn WITHOUT excluding each pair's positive (a
    collision demotes one true context draw to ~uniform noise at
    unigram-table probability — word2vec.c itself merely skips such
    draws). Row updates still go through the clipped deduplicating
    scatter, so determinism and the divergence guard are unchanged."""
    b = centers.shape[0]
    d = syn0.shape[1]
    g, n_neg = negs.shape
    group = b // g
    valid = (jnp.arange(b) < nv).astype(jnp.float32)
    h = syn0[centers]                                  # [B, D]
    wt = syn1[contexts]                                # [B, D]
    # positive pair
    lp = jnp.sum(h * wt, axis=-1)
    gp = (1.0 - jax.nn.sigmoid(lp)) * valid * lr       # [B]
    dh = gp[:, None] * wt
    dwt = gp[:, None] * h
    # shared negatives: batched matmuls over [G, group, D]
    wn = syn1[negs.reshape(-1)].reshape(g, n_neg, d)   # [G, NEG, D]
    hg = h.reshape(g, group, d)
    ln = jnp.einsum("gbd,gnd->gbn", hg, wn)
    gn = (-jax.nn.sigmoid(ln)) * valid.reshape(g, group, 1) * lr
    dh = dh + jnp.einsum("gbn,gnd->gbd", gn, wn).reshape(b, d)
    dwn = jnp.einsum("gbn,gbd->gnd", gn, hg)           # [G, NEG, D]
    mr = _max_row_norm(lr, d)
    syn1 = _clipped_scatter(syn1, contexts, dwt, mr)
    syn1 = _clipped_scatter(syn1, negs.reshape(-1),
                            dwn.reshape(-1, d), mr)
    syn0 = _clipped_scatter(syn0, centers, dh, mr)
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def skipgram_scan_step_shared(syn0, syn1,
                              centers,   # [D, B] int32
                              contexts,  # [D, B] int32
                              negs,      # [D, G, NEG] int32
                              n_valid,   # [D] int32
                              lrs):      # [D] float32
    def body(carry, chunk):
        s0, s1 = carry
        cen, ctx, ng, nv, lr = chunk
        s0, s1 = _sg_update_shared(s0, s1, cen, ctx, ng, nv, lr)
        return (s0, s1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (centers, contexts, negs, n_valid, lrs))
    return syn0, syn1
