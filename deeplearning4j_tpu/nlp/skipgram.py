"""Batched SkipGram / CBOW device kernels.

TPU-native replacement for the reference's native aggregate ops: the
reference batches (center, context) pairs into ``AggregateSkipGram`` /
``AggregateCBOW`` and executes them in C++ via
``Nd4j.getExecutioner().exec(batches)``
(models/embeddings/learning/impl/elements/SkipGram.java:176,271; CBOW.java).

Here the same batching idea becomes ONE jitted step per batch: gather the
center rows from syn0 and the target rows (negative samples or Huffman
inner nodes) from syn1, compute the sigmoid-gradient for every pair at
once on the MXU, and scatter-add the updates back. Duplicate indices in a
batch are handled correctly by XLA's scatter-add. Buffers are donated so
the embedding tables are updated in place on device.

The math (per pair, label y ∈ {0,1}, lr α):
    g = (y − σ(syn0[c]·syn1[t])) · α
    syn1[t] += g · syn0[c]
    syn0[c] += g · syn1[t]        (pre-update value, as in word2vec.c)
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, donate_argnums=(0, 1))
def skipgram_step(syn0: jax.Array, syn1: jax.Array,
                  centers: jax.Array,      # [B] int32
                  targets: jax.Array,      # [B, K] int32
                  labels: jax.Array,       # [B, K] float32 (1=pos, 0=neg)
                  mask: jax.Array,         # [B, K] float32
                  lr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One batched SkipGram update (negative sampling or hierarchical
    softmax — identical math, different targets/labels)."""
    h = syn0[centers]                                  # [B, D]
    w = syn1[targets]                                  # [B, K, D]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (labels - jax.nn.sigmoid(logits)) * mask * lr  # [B, K]
    dh = jnp.einsum("bk,bkd->bd", g, w)                # grad wrt syn0 rows
    dw = g[..., None] * h[:, None, :]                  # [B, K, D]
    d = syn0.shape[1]
    syn1 = syn1.at[targets.reshape(-1)].add(
        dw.reshape(-1, d).astype(syn1.dtype))
    syn0 = syn0.at[centers].add(dh.astype(syn0.dtype))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_step(syn0: jax.Array, syn1: jax.Array,
              context: jax.Array,       # [B, W] int32 context word rows
              context_mask: jax.Array,  # [B, W] float32
              targets: jax.Array,       # [B, K] int32
              labels: jax.Array,        # [B, K] float32
              mask: jax.Array,          # [B, K] float32
              lr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One batched CBOW update: h = mean(context rows); the syn0 gradient
    is broadcast back to every context word (reference: CBOW.java via
    AggregateCBOW)."""
    cvecs = syn0[context]                               # [B, W, D]
    denom = jnp.maximum(context_mask.sum(-1, keepdims=True), 1.0)
    h = (cvecs * context_mask[..., None]).sum(1) / denom  # [B, D]
    w = syn1[targets]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (labels - jax.nn.sigmoid(logits)) * mask * lr
    dh = jnp.einsum("bk,bkd->bd", g, w) / denom          # [B, D]
    dw = g[..., None] * h[:, None, :]
    d = syn0.shape[1]
    syn1 = syn1.at[targets.reshape(-1)].add(
        dw.reshape(-1, d).astype(syn1.dtype))
    dctx = (dh[:, None, :] * context_mask[..., None]).reshape(-1, d)
    syn0 = syn0.at[context.reshape(-1)].add(dctx.astype(syn0.dtype))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0,))
def infer_step(docvec: jax.Array,        # [D] the one trainable vector
               syn1: jax.Array,          # frozen
               targets: jax.Array,       # [P, K]
               labels: jax.Array,
               mask: jax.Array,
               lr: jax.Array) -> jax.Array:
    """ParagraphVectors.inferVector inner step: train a single new doc
    vector against a frozen syn1 (reference: ParagraphVectors.java
    inferVector)."""
    w = syn1[targets]                                   # [P, K, D]
    logits = jnp.einsum("d,pkd->pk", docvec, w)
    g = (labels - jax.nn.sigmoid(logits)) * mask * lr
    return docvec + jnp.einsum("pk,pkd->d", g, w).astype(docvec.dtype)


class PairBatcher:
    """Host-side accumulator of (center, targets, labels) rows, flushed to
    the device kernel when full — the analog of the reference's batch list
    handed to the native executioner (SkipGram.java:176-186)."""

    def __init__(self, batch_size: int, k: int):
        self.batch_size = batch_size
        self.k = k
        self.centers = np.zeros(batch_size, np.int32)
        self.targets = np.zeros((batch_size, k), np.int32)
        self.labels = np.zeros((batch_size, k), np.float32)
        self.mask = np.zeros((batch_size, k), np.float32)
        self.n = 0

    def add(self, center: int, targets: np.ndarray, labels: np.ndarray):
        i = self.n
        kk = min(len(targets), self.k)
        self.centers[i] = center
        self.targets[i, :kk] = targets[:kk]
        self.labels[i, :kk] = labels[:kk]
        self.mask[i, :kk] = 1.0
        if kk < self.k:
            self.targets[i, kk:] = 0
            self.labels[i, kk:] = 0.0
            self.mask[i, kk:] = 0.0
        self.n += 1
        return self.n >= self.batch_size

    def take(self):
        out = (self.centers.copy(), self.targets.copy(),
               self.labels.copy(), self.mask.copy(), self.n)
        # zero masks beyond fill point so a partial flush is inert
        if self.n < self.batch_size:
            out[3][self.n:] = 0.0
        self.n = 0
        self.mask[:] = 0.0
        return out


def negative_sample_targets(pos: int, table: np.ndarray, n_neg: int,
                            rng: np.random.Generator
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """1 positive + n_neg negatives drawn from the unigram^0.75 table.
    Negatives colliding with the positive are redrawn (word2vec.c skips
    target==word), so a row never trains the same target toward both
    labels at once."""
    negs = table[rng.integers(0, len(table), n_neg)]
    for _ in range(4):
        bad = negs == pos
        if not bad.any():
            break
        negs[bad] = table[rng.integers(0, len(table), int(bad.sum()))]
    if (negs == pos).any():  # tiny vocab: fall back to cycling indices
        n_words = int(table.max()) + 1
        negs[negs == pos] = (pos + 1) % max(n_words, 2)
    targets = np.concatenate(([pos], negs)).astype(np.int32)
    labels = np.zeros(1 + n_neg, np.float32)
    labels[0] = 1.0
    return targets, labels


def hs_targets(vw, max_len: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Hierarchical-softmax targets: Huffman inner nodes with label
    1−code (word2vec convention)."""
    points = np.asarray(vw.points, np.int32)
    labels = 1.0 - np.asarray(vw.codes, np.float32)
    if max_len is not None:
        points, labels = points[:max_len], labels[:max_len]
    return points, labels
