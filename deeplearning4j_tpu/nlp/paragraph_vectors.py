"""ParagraphVectors (doc2vec): label-aware embeddings.

Analog of the reference's models/paragraphvectors/ParagraphVectors.java
with the two sequence learning algorithms from
models/embeddings/learning/impl/sequence/ (SURVEY §2.7):
  - DBOW (DBOW.java): the document label is a center "word" predicting
    every word in the document — plain SkipGram pairs with the label row.
  - DM (DM.java): the label vector joins the context window in a CBOW
    step predicting the center word.
Label vectors live in the same syn0 table as word vectors (as in the
reference, where labels are special vocab elements), so both algorithms
reuse the jitted kernels unchanged. ``infer_vector`` trains a fresh row
against frozen syn1 (ParagraphVectors.java inferVector).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Union

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import skipgram as sk
from deeplearning4j_tpu.nlp.sentence_iterators import (
    LabelAwareIterator,
    LabelledDocument,
    SentenceLabelledIterator,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class ParagraphVectors(Word2Vec):
    def __init__(self, dm: bool = False, **kwargs):
        kwargs.setdefault("use_cbow", dm)
        # DBOW rides the shared _PairStream; keep the exact per-pair
        # negative draws here (the round-4 grouped shared-negative
        # kernel is validated for Word2Vec SGNS, not for PV) — opt in
        # explicitly with shared_negatives=True
        kwargs.setdefault("shared_negatives", False)
        super().__init__(**kwargs)
        self.dm = dm
        self._label_set = set()

    # ---- corpus handling -------------------------------------------------
    def _docs(self, corpus) -> List[LabelledDocument]:
        if isinstance(corpus, LabelAwareIterator):
            return list(corpus)
        docs = list(corpus)
        if docs and isinstance(docs[0], str):
            return list(SentenceLabelledIterator(docs))
        return docs

    def fit(self, corpus: Union[LabelAwareIterator, Iterable[str],
                                Iterable[LabelledDocument]]):
        docs = self._docs(corpus)
        tokenized = [(self.tokenizer_factory.create(d.content).get_tokens(),
                      d.labels) for d in docs]
        self._label_set = {lb for _t, lbs in tokenized for lb in lbs}
        if self.vocab is None:
            super(Word2Vec, self).build_vocab(
                [t for t, _ in tokenized],
                special_tokens=sorted(self._label_set))
        if self.syn0 is None:
            self._init_tables()
        # lr anneal denominator: DBOW sees each token twice per epoch
        # (once as a label-pair add, once in the joint word pass)
        per_epoch = sum(len(t) for t, _ in tokenized)
        total = max(1, per_epoch * self.epochs * (1 if self.dm else 2))
        # fast paths only when no subclass customizes the doc-level
        # hooks either (same trap _fast_sgns_ok documents for
        # _train_sequence overrides)
        doc_hooks_ok = (
            type(self)._train_dbow is ParagraphVectors._train_dbow
            and type(self)._train_dm is ParagraphVectors._train_dm)
        if self._fast_hooks_ok() and doc_hooks_ok:
            if self.dm:
                lidx_lists = [
                    [i for i in (self.vocab.index_of(lb) for lb in lbs)
                     if i >= 0] for _t, lbs in tokenized]
                return self._fit_fast_cbow(
                    [t for t, _ in tokenized], total,
                    extra_per_seq=lidx_lists)
            return self._fit_fast_dbow(tokenized, total)
        k = self._k()
        batcher = sk.PairBatcher(self.batch_size, k)
        seen = 0
        for _ep in range(self.epochs):
            for tokens, labels in tokenized:
                idxs = self._indices(tokens)
                lidxs = [self.vocab.index_of(lb) for lb in labels]
                lidxs = [i for i in lidxs if i >= 0]
                if self.dm:
                    seen = self._train_dm(idxs, lidxs, seen, total)
                else:
                    seen = self._train_dbow(idxs, lidxs, batcher, seen, total)
                    # words also train among themselves (reference trains
                    # word vectors jointly unless trainWordVectors=false)
                    seen = super(Word2Vec, self)._train_sequence(
                        idxs, batcher, seen, total)
        self._flush(batcher, self._lr(seen, total))
        return self

    def _fit_fast_dbow(self, tokenized, total: int):
        """Corpus-level vectorized DBOW (round 6): ONE vocab-lookup
        pass flattens the corpus (``_encode_corpus_flat``), the label
        of every token is materialized as a per-token array per label
        slot (one numpy gather each), and both passes stream as
        corpus-level numpy over ``_window_slabs`` — the same walk the
        SGNS and CBOW producers share, no per-doc Python. Per slab:
        the (label, word) product (the doc vector predicts each of its
        words — DBOW.java), then the joint word-window pairs
        (trainWordVectors=true semantics). The previous per-doc
        producer was the measured host bound at 249k tokens/s
        (PERF_ANALYSIS r5)."""
        from deeplearning4j_tpu.nlp.sequence_vectors import _PairStream
        W = self.window_size
        # total already carries DBOW's x2 token factor; the pair count
        # is ~tokens * (W + 2), so halve before scaling
        chunk = self._pair_chunk_size((total // 2) * (W + 2))

        seqs = [t for t, _ in tokenized]
        ids_all, seq_all = self._encode_corpus_flat(seqs)
        lidx_lists = [
            [i for i in (self.vocab.index_of(lb) for lb in labels)
             if i >= 0] for _t, labels in tokenized]
        # label slot j -> per-token label row (-1 where the doc has
        # fewer than j+1 labels); docs rarely carry more than one
        max_l = max((len(ls) for ls in lidx_lists), default=0)
        extras = []
        for j in range(max_l):
            lab = np.full(len(tokenized), -1, np.int32)
            for d, ls in enumerate(lidx_lists):
                if len(ls) > j:
                    lab[d] = ls[j]
            extras.append(lab[seq_all])
        extras = tuple(extras)

        if self.pairgen != "legacy":
            from deeplearning4j_tpu.nlp import pairgen as pg
            walker = pg.CorpusWalker(
                self, ids_all, seq_all, extras=extras,
                force_numpy=self.pairgen == "numpy")
            n_neg = self._fused_n_neg(chunk)

            def produce(sink):
                stream = _PairStream(self, chunk, total, sink=sink,
                                     n_neg=n_neg)
                for ep in range(self.epochs):
                    view = walker.epoch(ep)
                    # one global pair counter per epoch, advanced in
                    # emission order: per slab the label rows (slot by
                    # slot), then the word-window pairs — so every pair
                    # owns a unique NEG-stream counter range
                    pair_base = 0
                    bounds = (view.slab_bounds() if view.n >= 2
                              else [(0, view.n)])
                    for lo, hi in bounds:
                        ids_slab = view.ids[lo:hi]
                        for lab in view.extras or ():
                            lab_s = lab[lo:hi]
                            lm = lab_s >= 0
                            if lm.all():
                                cen, ctx, tk = lab_s, ids_slab, \
                                    len(lab_s)
                            else:
                                cen, ctx, tk = lab_s[lm], \
                                    ids_slab[lm], int(lm.sum())
                            negs = (view.negatives(ctx, n_neg,
                                                   pair_base)
                                    if n_neg and len(ctx) else None)
                            pair_base += len(cen)
                            stream.push(cen, ctx, tokens=tk,
                                        negs=negs)
                        if view.n >= 2:
                            c, x, negs = view.walk(lo, hi,
                                                   n_neg=n_neg,
                                                   pair_base=pair_base)
                            pair_base += len(c)
                            stream.push(c, x, tokens=hi - lo,
                                        negs=negs)
                        else:
                            stream.seen += hi - lo
                stream.finish()
        else:
            def produce(sink):
                stream = _PairStream(self, chunk, total, sink=sink)
                for ids, lo, hi, grid, valid, labs in \
                        self._window_slabs(ids_all, seq_all,
                                           extras=extras):
                    ids_slab = ids[lo:hi]
                    for lab in labs:
                        lm = lab >= 0
                        # per-doc accounting advanced n tokens per label
                        # slot; spread the same progress over these
                        # pairs. All-labeled slabs (the common
                        # single-label-per-doc corpus) skip the two
                        # boolean gathers.
                        if lm.all():
                            stream.push(lab, ids_slab, tokens=len(lab))
                        else:
                            stream.push(lab[lm], ids_slab[lm],
                                        tokens=int(lm.sum()))
                    if valid is not None:
                        stream.push(
                            np.repeat(ids_slab, valid.sum(axis=1)),
                            ids[grid[valid]], tokens=hi - lo)
                    else:
                        stream.seen += hi - lo
                stream.finish()

        if self.overlap_pairgen:
            self._run_overlapped(produce)
        else:
            produce(None)
        return self

    def _train_dbow(self, idxs, lidxs, batcher, seen, total):
        for label_row in lidxs:
            for w in idxs:
                self._add_pair(label_row, w, batcher, seen, total)
                seen += 1
        return seen

    def _train_dm(self, idxs, lidxs, seen, total):
        window = self.window_size
        ctx_w = 2 * window + len(lidxs)
        if getattr(self, "_cbow_buf", None) is None or \
                self._cbow_buf.ctx_w < ctx_w:
            from deeplearning4j_tpu.nlp.word2vec import _CbowBatcher
            if getattr(self, "_cbow_buf", None) is not None:
                # drain pending pairs before swapping in a wider batcher
                self._flush_cbow(self._cbow_buf, self._lr(seen, total))
            self._cbow_buf = _CbowBatcher(self.batch_size, ctx_w, self._k())
        buf = self._cbow_buf
        for pos, center in enumerate(idxs):
            lo, hi = self._window_bounds(pos, len(idxs))
            ctx = [idxs[c] for c in range(lo, hi) if c != pos] + lidxs
            if not ctx:
                seen += 1
                continue
            if self.use_hs:
                targets, labels = sk.hs_targets(
                    self.vocab.element_at_index(center))
            else:
                targets, labels = sk.negative_sample_targets(
                    center, self._table, self.negative, self._rng)
            if buf.add(ctx, targets, labels):
                self._flush_cbow(buf, self._lr(seen, total))
            seen += 1
        return seen

    # ---- serving ---------------------------------------------------------
    def labels(self) -> List[str]:
        return sorted(self._label_set)

    def get_label_vector(self, label: str) -> np.ndarray:
        return self.get_word_vector(label)

    def infer_vector(self, text: str, steps: int = 10,
                     learning_rate: Optional[float] = None) -> np.ndarray:
        """Train one fresh vector for unseen text against frozen syn1
        (reference: ParagraphVectors.inferVector)."""
        lr = learning_rate or self.learning_rate
        tokens = self.tokenizer_factory.create(text).get_tokens()
        idxs = [self.vocab.index_of(t) for t in tokens]
        idxs = [i for i in idxs if i >= 0]
        rng = np.random.default_rng(0)
        # jnp.array (owning copy): infer_step donates the doc vector, so
        # it must not zero-copy adopt the numpy temp (use-after-free —
        # see SequenceVectors._init_tables)
        vec = jnp.array(((rng.random(self.layer_size) - 0.5)
                         / self.layer_size).astype(np.float32))
        if not idxs:
            return np.asarray(vec)  # host-sync-ok: user egress
        k = self._k()
        # pad rows to a power-of-two bucket so infer_step compiles once
        # per bucket, not once per distinct text length
        rows = 1 << (len(idxs) - 1).bit_length()
        targets = np.zeros((rows, k), np.int32)
        labels = np.zeros((rows, k), np.float32)
        mask = np.zeros((rows, k), np.float32)
        for _step in range(steps):
            mask[:] = 0.0
            for p, w in enumerate(idxs):
                if self.use_hs:
                    t, l = sk.hs_targets(self.vocab.element_at_index(w))
                else:
                    t, l = sk.negative_sample_targets(
                        w, self._table, self.negative, rng)
                kk = min(len(t), k)
                targets[p, :kk], labels[p, :kk] = t[:kk], l[:kk]
                mask[p, :kk] = 1.0
            vec = sk.infer_step(vec, self.syn1, jnp.asarray(targets),
                                jnp.asarray(labels), jnp.asarray(mask),
                                jnp.float32(lr))
        return np.asarray(vec)  # host-sync-ok: user-facing egress

    def similarity_to_label(self, text: str, label: str) -> float:
        v = self.infer_vector(text)
        lv = self.get_label_vector(label)
        den = np.linalg.norm(v) * np.linalg.norm(lv)
        return float(v @ lv / den) if den else 0.0  # host-sync-ok: host numpy

    def predict(self, text: str) -> str:
        """Nearest label for unseen text (reference:
        ParagraphVectors.predict)."""
        v = self.infer_vector(text)
        best, best_sim = None, -np.inf
        for lb in self.labels():
            lv = self.get_label_vector(lb)
            den = np.linalg.norm(v) * np.linalg.norm(lv)
            s = float(v @ lv / den) if den else 0.0  # host-sync-ok: host numpy
            if s > best_sim:
                best, best_sim = lb, s
        return best
