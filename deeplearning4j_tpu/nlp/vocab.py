"""Vocabulary construction + Huffman coding.

Analog of the reference's models/word2vec/wordstore/ (VocabConstructor.java:32,
VocabCache.java, inmemory/AbstractCache.java) and word2vec/Huffman.java
(SURVEY §2.7, §3.6): scan a token stream, count frequencies, apply a
min-frequency cutoff, and build the Huffman tree used by hierarchical
softmax (codes/points per word, as in the reference's VocabWord).
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


@dataclasses.dataclass
class VocabWord:
    """reference: models/word2vec/VocabWord.java — word + frequency +
    Huffman code/points filled in by Huffman.build()."""
    word: str
    count: int = 0
    index: int = -1
    codes: List[int] = dataclasses.field(default_factory=list)
    points: List[int] = dataclasses.field(default_factory=list)


class VocabCache:
    """In-memory vocab store (reference: wordstore/inmemory/
    AbstractCache.java). Words are index-addressable; index order is
    descending frequency (ties by first occurrence)."""

    def __init__(self):
        self._words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_word_count = 0

    def add_token(self, vw: VocabWord):
        vw.index = len(self._words)
        self._words.append(vw)
        self._by_word[vw.word] = vw
        # word->index cache (built lazily by _encode_corpus_flat)
        self._index_by_word = None

    def contains_word(self, word: str) -> bool:
        return word in self._by_word

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._by_word.get(word)

    def index_of(self, word: str) -> int:
        vw = self._by_word.get(word)
        return -1 if vw is None else vw.index

    def word_at_index(self, idx: int) -> str:
        return self._words[idx].word

    def element_at_index(self, idx: int) -> VocabWord:
        return self._words[idx]

    def num_words(self) -> int:
        return len(self._words)

    def words(self) -> List[str]:
        return [w.word for w in self._words]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._words)

    def word_frequency(self, word: str) -> int:
        vw = self._by_word.get(word)
        return 0 if vw is None else vw.count

    def unigram_table(self, table_size: int = 1_000_000,
                      power: float = 0.75) -> np.ndarray:
        """Negative-sampling table: word index drawn ∝ count^0.75
        (reference builds this natively inside AggregateSkipGram;
        word2vec.c heritage)."""
        counts = np.array([w.count for w in self._words], dtype=np.float64)
        probs = counts ** power
        probs /= probs.sum()
        return np.random.default_rng(12345).choice(
            len(self._words), size=table_size, p=probs).astype(np.int32)


class VocabConstructor:
    """Corpus scan → VocabCache (reference: wordstore/
    VocabConstructor.java:32 buildJointVocabulary)."""

    def __init__(self, min_word_frequency: int = 1,
                 stop_words: Optional[Iterable[str]] = None):
        self.min_word_frequency = min_word_frequency
        self.stop_words = frozenset(stop_words or ())

    def build_vocab(self, token_sequences: Iterable[List[str]],
                    special_tokens: Iterable[str] = ()) -> VocabCache:
        counts: Counter = Counter()
        total = 0
        for seq in token_sequences:
            for tok in seq:
                if tok and tok not in self.stop_words:
                    counts[tok] += 1
                    total += 1
        cache = VocabCache()
        # special tokens (e.g. ParagraphVectors labels) bypass the cutoff
        for tok in special_tokens:
            if tok not in counts:
                counts[tok] = 1
        order = sorted(counts.items(), key=lambda kv: (-kv[1],))
        specials = set(special_tokens)
        for word, count in order:
            if count >= self.min_word_frequency or word in specials:
                cache.add_token(VocabWord(word=word, count=count))
        cache.total_word_count = total
        return cache


class Huffman:
    """Huffman tree over vocab frequencies → per-word binary code + inner
    node path (reference: models/word2vec/Huffman.java). ``points[i]`` are
    inner-node rows of syn1, ``codes[i]`` the branch bits."""

    MAX_CODE_LENGTH = 40

    def __init__(self, words: List[VocabWord]):
        self.words = words

    def build(self):
        n = len(self.words)
        if n == 0:
            return
        if n == 1:
            self.words[0].codes = [0]
            self.words[0].points = [0]
            return
        # heap of (count, tiebreak, node_id); leaves are 0..n-1, inner n..2n-2
        heap = [(w.count, i, i) for i, w in enumerate(self.words)]
        heapq.heapify(heap)
        parent = {}
        bit = {}
        next_id = n
        while len(heap) > 1:
            c1, _, a = heapq.heappop(heap)
            c2, _, b = heapq.heappop(heap)
            parent[a] = next_id
            parent[b] = next_id
            bit[a] = 0
            bit[b] = 1
            heapq.heappush(heap, (c1 + c2, next_id, next_id))
            next_id += 1
        root = heap[0][2]
        for i, w in enumerate(self.words):
            codes: List[int] = []
            points: List[int] = []
            node = i
            while node != root:
                codes.append(bit[node])
                node = parent[node]
                points.append(node - n)  # inner-node index into syn1
            codes.reverse()
            points.reverse()
            w.codes = codes[: self.MAX_CODE_LENGTH]
            w.points = points[: self.MAX_CODE_LENGTH]
