"""Cluster text pipeline — the Spark-NLP analog (VERDICT missing#7).

Reference: dl4j-spark-nlp's ``TextPipeline``
(/root/reference/deeplearning4j-scaleout/spark/dl4j-spark-nlp/src/main/
java/org/deeplearning4j/spark/text/functions/TextPipeline.java:48 —
tokenize per partition, accumulate word counters, filter by min word
frequency, build the shared vocab) and ``Word2VecPerformer`` (same tree
— per-partition skip-gram training against broadcast weights, merged by
the parameter-averaging master).

TPU-native redesign: the "cluster" is host processes around a device
mesh, not Spark executors. Map and reduce are explicit:

- ``TextPipeline``: shards a corpus, tokenizes + counts per shard (the
  map), merges counters into one ``VocabCache`` (the reduce) — bitwise
  identical to the single-host vocab build.
- ``DistributedWord2Vec``: one ``Word2Vec`` worker per shard, all seeded
  from the same initial tables; each round every worker trains its shard
  (the vectorized SGNS device loop), then syn0/syn1 are parameter-
  averaged — the Spark master's ``averageAndPropagate`` semantics. On a
  real multi-host pod each worker is a process with its own corpus
  shard; here workers run in one process over the corpus shards, which
  is the same math (the reference's local[N] test mode).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class TextPipeline:
    """Sharded tokenize → count → filter → vocab (TextPipeline.java:48)."""

    def __init__(self, num_shards: int = 4,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1,
                 stop_words: Iterable[str] = ()):
        self.num_shards = max(1, num_shards)
        self.tokenizer_factory = (tokenizer_factory
                                  or DefaultTokenizerFactory())
        self.min_word_frequency = min_word_frequency
        self.stop_words = frozenset(stop_words)

    def shard(self, corpus: Iterable[str]) -> List[List[str]]:
        shards: List[List[str]] = [[] for _ in range(self.num_shards)]
        for i, sentence in enumerate(corpus):
            shards[i % self.num_shards].append(sentence)
        return shards

    def tokenize_shard(self, sentences: Sequence[str]) -> List[List[str]]:
        """The per-partition map: raw sentences → token sequences.
        Empty results are KEPT (as []) so local indices still invert the
        round-robin sharding for count_shard's position keys."""
        out = []
        for s in sentences:
            out.append([t for t in
                        self.tokenizer_factory.create(s).get_tokens()
                        if t and t not in self.stop_words])
        return out

    def count_shard(self, token_seqs: Iterable[Sequence[str]],
                    shard_index: int = 0) -> dict:
        """Per-partition word counters (the accumulator): word →
        [count, first_global_position]. The position key inverts the
        round-robin sharding (global sentence = local_j * num_shards +
        shard_index), so the reduce can break frequency ties in original
        corpus-appearance order — the single-host constructor's Counter
        insertion order."""
        counts: dict = {}
        for j, seq in enumerate(token_seqs):
            sent = j * self.num_shards + shard_index
            for ti, t in enumerate(seq):
                entry = counts.get(t)
                if entry is None:
                    counts[t] = [1, (sent, ti)]
                else:
                    entry[0] += 1
        return counts

    def reduce_vocab(self, shard_counts: Sequence[dict]) -> VocabCache:
        """Merge counters, apply min frequency; ordering = count desc
        with ties in first-appearance order — index-identical to the
        single-host VocabConstructor (Huffman codes / syn1 rows line up
        across the two build paths)."""
        merged: dict = {}
        for counts in shard_counts:
            for w, (c, first) in counts.items():
                entry = merged.get(w)
                if entry is None:
                    merged[w] = [c, first]
                else:
                    entry[0] += c
                    entry[1] = min(entry[1], first)
        vocab = VocabCache()
        items = sorted(merged.items(), key=lambda kv: (-kv[1][0],
                                                       kv[1][1]))
        for w, (c, _first) in items:
            if c >= self.min_word_frequency:
                vocab.add_token(VocabWord(word=w, count=c))
        vocab.total_word_count = sum(c for c, _ in merged.values())
        return vocab

    def build_vocab(self, corpus: Iterable[str]) -> VocabCache:
        shards = self.shard(corpus)
        counts = [self.count_shard(self.tokenize_shard(s), i)
                  for i, s in enumerate(shards)]
        return self.reduce_vocab(counts)


class DistributedWord2Vec:
    """Data-parallel Word2Vec over corpus shards with parameter
    averaging (Word2VecPerformer + ParameterAveraging master analog)."""

    def __init__(self, num_workers: int = 4, averaging_rounds: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 **w2v_kwargs):
        self.num_workers = max(1, num_workers)
        self.averaging_rounds = max(1, averaging_rounds)
        self.w2v_kwargs = dict(w2v_kwargs)
        self.pipeline = TextPipeline(
            num_shards=self.num_workers,
            tokenizer_factory=tokenizer_factory,
            min_word_frequency=self.w2v_kwargs.get("min_word_frequency", 1),
            stop_words=self.w2v_kwargs.get("stop_words", ()))
        self.model: Optional[Word2Vec] = None

    def fit(self, corpus: Iterable[str]) -> Word2Vec:
        sentences = list(corpus)
        shards_raw = self.pipeline.shard(sentences)
        token_shards = [self.pipeline.tokenize_shard(s)
                        for s in shards_raw]
        vocab = self.pipeline.reduce_vocab(
            [self.pipeline.count_shard(ts, i)
             for i, ts in enumerate(token_shards)])
        token_shards = [[s for s in ts if s] for ts in token_shards]

        # global model: shared vocab + one set of initial tables
        master = Word2Vec(**self.w2v_kwargs)
        master.vocab = vocab
        if master.use_hs:
            Huffman(vocab.vocab_words()).build()
            master._max_code_len = max(
                (len(w.codes) for w in vocab.vocab_words()), default=1)
        master._init_tables()

        epochs = master.epochs
        for _round in range(self.averaging_rounds):
            syn0s, syn1s = [], []
            for wid, shard in enumerate(token_shards):
                if not shard:
                    continue
                worker = Word2Vec(**{**self.w2v_kwargs,
                                     "seed": master.seed + wid})
                worker.vocab = vocab
                worker._max_code_len = master._max_code_len
                worker._table = master._table
                if master.use_hs:
                    # share the device-resident Huffman matrices
                    # (read-only; the kernels never donate them)
                    worker._hs_points = master._hs_points
                    worker._hs_labels = master._hs_labels
                    worker._hs_mask = master._hs_mask
                worker.epochs = max(1, epochs // self.averaging_rounds)
                # broadcast current globals (the Spark broadcast step) —
                # as COPIES: the device hot loop donates its syn buffers,
                # so sharing one array across workers would hand worker 0
                # the master's buffer to destroy
                import jax.numpy as jnp
                worker.syn0 = jnp.array(master.syn0)
                worker.syn1 = jnp.array(master.syn1)
                worker.fit(shard)
                syn0s.append(np.asarray(worker.syn0))
                syn1s.append(np.asarray(worker.syn1))
            if syn0s:
                import jax.numpy as jnp
                # jnp.array (owning copies): the averaged tables feed
                # models whose kernels donate syn0/syn1; adopting the
                # np.mean temps zero-copy risks a use-after-free (see
                # SequenceVectors._init_tables)
                master.syn0 = jnp.array(np.mean(syn0s, axis=0))
                master.syn1 = jnp.array(np.mean(syn1s, axis=0))
        self.model = master
        return master
