"""Word-vector serialization.

Analog of the reference's models/embeddings/loader/WordVectorSerializer.java:87
(SURVEY §2.7): save/load in the classic word2vec text format (one
"word v1 v2 ..." line per word, optional gzip) plus a fast npz binary.
Loaders return StaticWord2Vec (serving) or hydrate a Word2Vec for
continued training.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import List, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import StaticWord2Vec, Word2Vec


def _open(path: str, mode: str):
    if path.endswith(".gz"):
        return gzip.open(path, mode + "t", encoding="utf-8")
    return open(path, mode, encoding="utf-8")


def write_word_vectors(model, path: str):
    """Text format, word2vec-compatible (reference:
    WordVectorSerializer.writeWordVectors)."""
    words = model.vocab.words() if hasattr(model, "vocab") else model._words
    mat = (model.word_vectors_matrix if hasattr(model, "word_vectors_matrix")
           else model._vectors)
    with _open(path, "w") as f:
        f.write(f"{len(words)} {mat.shape[1]}\n")
        for i, w in enumerate(words):
            vec = " ".join(f"{x:.6g}" for x in mat[i])
            f.write(f"{w.replace(' ', '_')} {vec}\n")


def read_word_vectors(path: str) -> StaticWord2Vec:
    """reference: WordVectorSerializer.readWord2VecModel (text path)."""
    words: List[str] = []
    rows: List[np.ndarray] = []
    with _open(path, "r") as f:
        header = f.readline().split()
        dim = int(header[1]) if len(header) == 2 else None
        if dim is None:       # headerless variant
            f.seek(0)
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append(np.asarray([float(x) for x in parts[1:]], np.float32))
    return StaticWord2Vec(words, np.stack(rows))


def write_full_model(model: Word2Vec, path: str):
    """Full training state (vocab counts + syn0/syn1 + hyperparams) so
    training can resume — analog of writeFullModel/zip format."""
    meta = {
        "layer_size": model.layer_size,
        "window_size": model.window_size,
        "negative": model.negative,
        "use_hs": model.use_hs,
        "learning_rate": model.learning_rate,
        "words": model.vocab.words(),
        "counts": [w.count for w in model.vocab.vocab_words()],
        "codes": [w.codes for w in model.vocab.vocab_words()],
        "points": [w.points for w in model.vocab.vocab_words()],
        "total_word_count": model.vocab.total_word_count,
    }
    np.savez_compressed(
        path,
        meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        syn0=np.asarray(model.syn0), syn1=np.asarray(model.syn1))


def read_full_model(path: str) -> Word2Vec:
    data = np.load(path if os.path.exists(path) else path + ".npz",
                   allow_pickle=False)
    meta = json.loads(bytes(data["meta"]).decode())
    model = Word2Vec(layer_size=meta["layer_size"],
                     window_size=meta["window_size"],
                     negative=meta["negative"],
                     use_hierarchic_softmax=meta["use_hs"],
                     learning_rate=meta["learning_rate"])
    cache = VocabCache()
    for w, c, codes, points in zip(meta["words"], meta["counts"],
                                   meta["codes"], meta["points"]):
        vw = VocabWord(word=w, count=c, codes=codes, points=points)
        cache.add_token(vw)
    cache.total_word_count = meta["total_word_count"]
    model.vocab = cache
    import jax.numpy as jnp
    # jnp.array (owning copy): a loaded model can train further, and the
    # kernels donate syn0/syn1 — adopting the npz-owned buffers zero-copy
    # would hand numpy-backed memory to the donation chain
    # (use-after-free; see SequenceVectors._init_tables)
    model.syn0 = jnp.array(data["syn0"])
    model.syn1 = jnp.array(data["syn1"])
    if not model.use_hs:
        model._table = cache.unigram_table()
    if model.use_hs:
        model._max_code_len = max(
            (len(c) for c in meta["codes"]), default=1)
    return model
