"""Score calculators for early stopping.

Analog of deeplearning4j-nn/.../earlystopping/scorecalc/
(DataSetLossCalculator.java, ClassificationScoreCalculator.java,
RegressionScoreCalculator.java, AutoencoderScoreCalculator.java).
Each computes one scalar score over a held-out iterator; ``minimize``
on the configuration decides the direction.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSetIterator
from deeplearning4j_tpu.evaluation.evaluation import (
    Evaluation,
    RegressionEvaluation,
)


class ScoreCalculator:
    def calculate_score(self, model) -> float:
        raise NotImplementedError

    @property
    def minimize_score(self) -> bool:
        return True


class DataSetLossCalculator(ScoreCalculator):
    """Loss over the iterator (scorecalc/DataSetLossCalculator.java):
    ``average=True`` → example-weighted mean, ``average=False`` → plain
    sum of per-batch losses, as the reference defines."""

    def __init__(self, iterator: DataSetIterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        for ds in self.iterator:
            bs = int(np.asarray(ds.features).shape[0])
            total += float(model.score(ds)) * (bs if self.average else 1.0)
            n += bs
        return total / max(n, 1) if self.average else total


class ClassificationScoreCalculator(ScoreCalculator):
    """Metric from a classification Evaluation; maximized
    (scorecalc/ClassificationScoreCalculator.java)."""

    ACCURACY = "accuracy"
    F1 = "f1"
    PRECISION = "precision"
    RECALL = "recall"

    def __init__(self, metric: str, iterator: DataSetIterator):
        self.metric = metric
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        ev: Evaluation = model.evaluate(self.iterator)
        return float(getattr(ev, self.metric)())

    @property
    def minimize_score(self) -> bool:
        return False


class RegressionScoreCalculator(ScoreCalculator):
    """Metric from RegressionEvaluation; minimized except for
    R²/correlation (scorecalc/RegressionScoreCalculator.java). Valid
    metric names are RegressionEvaluation method names:
    "mean_squared_error", "mean_absolute_error",
    "root_mean_squared_error", "r_squared", "pearson_correlation",
    "average_mean_squared_error"."""

    _MAXIMIZED = ("r_squared", "pearson_correlation")

    def __init__(self, metric: str, iterator: DataSetIterator):
        if not hasattr(RegressionEvaluation, metric):
            raise ValueError(
                f"unknown regression metric {metric!r}; expected a "
                "RegressionEvaluation method name such as "
                "'mean_squared_error' or 'r_squared'")
        self.metric = metric
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        ev: RegressionEvaluation = model.evaluate_regression(self.iterator)
        return float(getattr(ev, self.metric)())

    @property
    def minimize_score(self) -> bool:
        return self.metric not in self._MAXIMIZED


class CustomScoreCalculator(ScoreCalculator):
    """Adapter for a plain callable ``model -> float``."""

    def __init__(self, fn: Callable, minimize: bool = True):
        self.fn = fn
        self._minimize = minimize

    def calculate_score(self, model) -> float:
        return float(self.fn(model))

    @property
    def minimize_score(self) -> bool:
        return self._minimize
