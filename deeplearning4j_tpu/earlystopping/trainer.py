"""Early-stopping trainer.

Analog of deeplearning4j-nn/.../earlystopping/trainer/
(BaseEarlyStoppingTrainer.java, EarlyStoppingTrainer.java,
EarlyStoppingGraphTrainer.java): drives its own epoch loop so
iteration-level conditions can break mid-epoch, evaluates the held-out
score every N epochs, keeps the best model via the saver, and restores it
into the result (SURVEY §5.3 — EarlyStopping restores best checkpoint).

One trainer serves both model classes (the functional core is shared).
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    TerminationReason,
)
from deeplearning4j_tpu.observe.tracer import get_tracer


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_data: DataSetIterator):
        self.config = config
        self.model = model
        self.train_data = train_data
        self.listener = None  # optional EarlyStoppingListener-style hook

    def set_listener(self, listener) -> None:
        self.listener = listener

    def _score_direction_minimize(self) -> bool:
        if self.config.score_calculator is not None:
            return self.config.score_calculator.minimize_score
        return self.config.minimize

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        minimize = self._score_direction_minimize()
        for c in cfg.epoch_terminations:
            c.initialize()
        for c in cfg.iteration_terminations:
            c.initialize()

        if self.model.train_state is None:
            self.model.init()

        best_score: Optional[float] = None
        best_epoch = -1
        score_vs_epoch = {}
        epoch = 0
        reason = None
        details = ""

        while True:
            # ---- one epoch, iteration conditions checked per minibatch --
            self.train_data.reset()
            terminated_iter = False
            for ds in self.train_data:
                self.model.fit(ds)
                last = self.model.score()
                for cond in cfg.iteration_terminations:
                    if cond.terminate(last):
                        terminated_iter = True
                        reason = TerminationReason.ITERATION_TERMINATION_CONDITION
                        details = str(cond)
                        break
                if terminated_iter:
                    break
            if terminated_iter:
                break

            # ---- held-out score + best-model tracking -------------------
            score = None
            tracer = get_tracer(self.model)
            if (cfg.score_calculator is not None
                    and epoch % cfg.evaluate_every_n_epochs == 0):
                with tracer.span("eval", cat="eval"):
                    score = cfg.score_calculator.calculate_score(self.model)
                score_vs_epoch[epoch] = score
                improved = (best_score is None
                            or (minimize and score < best_score)
                            or (not minimize and score > best_score))
                if improved:
                    best_score = score
                    best_epoch = epoch
                    with tracer.span("checkpoint", cat="io"):
                        cfg.saver.save_best_model(self.model, score)
                if self.listener is not None:
                    self.listener(epoch, score, self.model)
            elif cfg.score_calculator is None:
                # no held-out calculator configured: the training loss is
                # the score by definition (reference default)
                score = self.model.score()

            if cfg.save_last_model:
                with tracer.span("checkpoint", cat="io"):
                    cfg.saver.save_latest_model(
                        self.model, score if score is not None
                        else self.model.score())

            # ---- epoch conditions ---------------------------------------
            # Score-based conditions only see the calculator's metric; on
            # non-evaluation epochs (score None) only epoch-count/time
            # conditions can fire — never the training loss masquerading
            # as the held-out metric.
            stop = False
            for cond in cfg.epoch_terminations:
                if score is None and getattr(cond, "score_based", True):
                    continue
                if cond.terminate(epoch, score, minimize):
                    stop = True
                    reason = TerminationReason.EPOCH_TERMINATION_CONDITION
                    details = str(cond)
                    break
            if stop:
                break
            epoch += 1

        best_model = cfg.saver.get_best_model()
        if best_model is None:
            best_model = self.model
            if best_score is None:
                best_score = float("nan")
                best_epoch = epoch
        return EarlyStoppingResult(
            termination_reason=reason or TerminationReason.ERROR,
            termination_details=details,
            score_vs_epoch=score_vs_epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None
            else float("nan"),
            total_epochs=epoch + 1,
            best_model=best_model,
        )


# Reference has a distinct class for ComputationGraph; same impl here.
EarlyStoppingGraphTrainer = EarlyStoppingTrainer
