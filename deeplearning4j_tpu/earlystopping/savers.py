"""Model savers for early stopping.

Analog of deeplearning4j-nn/.../earlystopping/saver/
(InMemoryModelSaver.java, LocalFileModelSaver.java, LocalFileGraphSaver
.java). One LocalFileModelSaver serves both model classes here — the
checkpoint format (models/serialization.py) is class-tagged.
"""

from __future__ import annotations

import os
from typing import Optional

from deeplearning4j_tpu.models import serialization


class ModelSaver:
    def save_best_model(self, model, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, model, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(ModelSaver):
    """Keeps clones in memory (saver/InMemoryModelSaver.java)."""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score: float) -> None:
        self._best = model.clone()

    def save_latest_model(self, model, score: float) -> None:
        self._latest = model.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver(ModelSaver):
    """Writes bestModel.bin / latestModel.bin under a directory
    (saver/LocalFileModelSaver.java — same file names)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def save_best_model(self, model, score: float) -> None:
        serialization.save_model(model, self._path("bestModel.bin"),
                                 save_updater=True)

    def save_latest_model(self, model, score: float) -> None:
        serialization.save_model(model, self._path("latestModel.bin"),
                                 save_updater=True)

    def _restore(self, name: str):
        path = self._path(name)
        if not os.path.exists(path):
            return None
        return serialization.restore_model(path, load_updater=True)

    def get_best_model(self):
        return self._restore("bestModel.bin")

    def get_latest_model(self):
        return self._restore("latestModel.bin")


# Alias for API parity with the reference's graph-specific saver.
LocalFileGraphSaver = LocalFileModelSaver
