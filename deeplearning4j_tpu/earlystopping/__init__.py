"""Early stopping (SURVEY §2.1: earlystopping/)."""

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    TerminationReason,
)
from deeplearning4j_tpu.earlystopping.savers import (
    InMemoryModelSaver,
    LocalFileGraphSaver,
    LocalFileModelSaver,
    ModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import (
    ClassificationScoreCalculator,
    CustomScoreCalculator,
    DataSetLossCalculator,
    RegressionScoreCalculator,
    ScoreCalculator,
)
from deeplearning4j_tpu.earlystopping.termination import (
    BestScoreEpochTerminationCondition,
    EpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    IterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochsTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.trainer import (
    EarlyStoppingGraphTrainer,
    EarlyStoppingTrainer,
)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult", "TerminationReason",
    "ModelSaver", "InMemoryModelSaver", "LocalFileModelSaver",
    "LocalFileGraphSaver", "ScoreCalculator", "DataSetLossCalculator",
    "ClassificationScoreCalculator", "RegressionScoreCalculator",
    "CustomScoreCalculator", "EpochTerminationCondition",
    "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochsTerminationCondition",
    "BestScoreEpochTerminationCondition", "IterationTerminationCondition",
    "MaxTimeIterationTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition", "EarlyStoppingTrainer",
    "EarlyStoppingGraphTrainer",
]
