"""Termination conditions for early stopping.

Analog of deeplearning4j-nn/.../earlystopping/termination/: epoch-level
(MaxEpochsTerminationCondition.java, ScoreImprovementEpochsTermination
Condition.java, BestScoreEpochTerminationCondition.java) and
iteration-level (MaxTimeIterationTerminationCondition.java,
MaxScoreIterationTerminationCondition.java, InvalidScoreIteration
TerminationCondition.java — the NaN/divergence guard, SURVEY §5.2).
"""

from __future__ import annotations

import math
import time


# ---- epoch-level --------------------------------------------------------

class EpochTerminationCondition:
    #: whether terminate() reads ``score``; conditions with score_based
    #: True are skipped on epochs where no held-out score was computed
    score_based = True

    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    score_based = False

    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        return epoch + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochsTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.max_no_improve = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self._best = None
        self._epochs_since = 0

    def initialize(self) -> None:
        self._best = None
        self._epochs_since = 0

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        if self._best is None:
            self._best = score
            return False
        improvement = (self._best - score) if minimize else (score - self._best)
        if improvement > self.min_improvement:
            self._best = score
            self._epochs_since = 0
            return False
        self._epochs_since += 1
        return self._epochs_since >= self.max_no_improve

    def __str__(self):
        return (f"ScoreImprovementEpochsTerminationCondition("
                f"{self.max_no_improve}, {self.min_improvement})")


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least as good as a target value."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = float(best_expected_score)

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        if minimize:
            return score <= self.best_expected_score
        return score >= self.best_expected_score

    def __str__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected_score})"


# ---- iteration-level ----------------------------------------------------

class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, last_minibatch_score: float) -> bool:
        raise NotImplementedError


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self) -> None:
        self._start = time.time()

    def terminate(self, last_minibatch_score: float) -> bool:
        if self._start is None:
            self._start = time.time()
        return (time.time() - self._start) >= self.max_seconds

    def __str__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Divergence guard: stop if the minibatch score explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, last_minibatch_score: float) -> bool:
        return last_minibatch_score > self.max_score

    def __str__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """NaN/Inf guard (termination/InvalidScoreIterationTerminationCondition
    .java) — the reference's divergence detector (SURVEY §5.2)."""

    def terminate(self, last_minibatch_score: float) -> bool:
        return math.isnan(last_minibatch_score) or math.isinf(
            last_minibatch_score)

    def __str__(self):
        return "InvalidScoreIterationTerminationCondition()"
