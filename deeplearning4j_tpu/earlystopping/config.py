"""Early-stopping configuration + result.

Analog of the reference's early-stopping subsystem
(deeplearning4j-nn/.../earlystopping/EarlyStoppingConfiguration.java and
EarlyStoppingResult.java): a builder gathering a model saver, a score
calculator, epoch/iteration termination conditions, and an evaluation
frequency; the trainer (earlystopping/trainer.py) drives the loop.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional

from deeplearning4j_tpu.earlystopping.savers import InMemoryModelSaver, ModelSaver
from deeplearning4j_tpu.earlystopping.scorecalc import ScoreCalculator
from deeplearning4j_tpu.earlystopping.termination import (
    EpochTerminationCondition,
    IterationTerminationCondition,
)


class TerminationReason(enum.Enum):
    """Mirrors EarlyStoppingResult.TerminationReason."""
    ERROR = "Error"
    ITERATION_TERMINATION_CONDITION = "IterationTerminationCondition"
    EPOCH_TERMINATION_CONDITION = "EpochTerminationCondition"


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: TerminationReason
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: object  # model instance (restored from saver)

    def __str__(self):
        return (f"EarlyStoppingResult(reason={self.termination_reason.value}, "
                f"details={self.termination_details}, "
                f"bestModelEpoch={self.best_model_epoch}, "
                f"bestModelScore={self.best_model_score:.6f}, "
                f"totalEpochs={self.total_epochs})")


class EarlyStoppingConfiguration:
    """Holds the full early-stopping recipe. Use ``Builder``."""

    def __init__(self, saver: ModelSaver,
                 score_calculator: Optional[ScoreCalculator],
                 epoch_terminations: List[EpochTerminationCondition],
                 iteration_terminations: List[IterationTerminationCondition],
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False,
                 minimize: bool = True):
        self.saver = saver
        self.score_calculator = score_calculator
        self.epoch_terminations = list(epoch_terminations)
        self.iteration_terminations = list(iteration_terminations)
        self.evaluate_every_n_epochs = evaluate_every_n_epochs
        self.save_last_model = save_last_model
        self.minimize = minimize

    class Builder:
        def __init__(self):
            self._saver: ModelSaver = InMemoryModelSaver()
            self._score_calc: Optional[ScoreCalculator] = None
            self._epoch_term: List[EpochTerminationCondition] = []
            self._iter_term: List[IterationTerminationCondition] = []
            self._eval_every = 1
            self._save_last = False
            self._minimize = True

        def model_saver(self, saver: ModelSaver):
            self._saver = saver
            return self

        def score_calculator(self, calc: ScoreCalculator):
            self._score_calc = calc
            return self

        def epoch_termination_conditions(self, *conds):
            self._epoch_term.extend(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._iter_term.extend(conds)
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._eval_every = int(n)
            return self

        def save_last_model(self, b: bool = True):
            self._save_last = b
            return self

        def minimize(self, b: bool = True):
            self._minimize = b
            return self

        def build(self) -> "EarlyStoppingConfiguration":
            return EarlyStoppingConfiguration(
                saver=self._saver,
                score_calculator=self._score_calc,
                epoch_terminations=self._epoch_term,
                iteration_terminations=self._iter_term,
                evaluate_every_n_epochs=self._eval_every,
                save_last_model=self._save_last,
                minimize=self._minimize,
            )
