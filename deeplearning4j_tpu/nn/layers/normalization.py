"""Normalization layers.

Analogs of the reference's ``BatchNormalization``
(deeplearning4j-nn/.../nn/layers/normalization/BatchNormalization.java:41,
cuDNN helper hook at :57) and ``LocalResponseNormalization``. Batch-norm
running statistics live in the layer **state** pytree (not params), updated
functionally during training — the analog of the reference's
``globalMean``/``globalVar`` params, but without in-place mutation so the
whole train step stays a pure jitted function.

Also includes LayerNorm — absent from the reference but required by the
transformer models this framework targets (BERT import path).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, LayerContext
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class BatchNormalization(Layer):
    """Normalizes over all axes except the last (feature/channel) axis —
    correct for both (N, F) dense and (N, H, W, C) NHWC conv activations."""
    decay: float = 0.9           # running-average momentum (reference: decay)
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False
    use_global_stats_in_train: bool = False  # reference: useLogStd/global flag

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _nf(self, input_type: InputType) -> int:
        return input_type.shape()[-1]

    def initialize(self, key, input_type):
        nf = self._nf(input_type)
        dt = self.param_dtype()
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.full((nf,), self.gamma_init, dt),
                "beta": jnp.full((nf,), self.beta_init, dt)}

    def init_state(self, input_type):
        nf = self._nf(input_type)
        return {"mean": jnp.zeros((nf,), jnp.float32),
                "var": jnp.ones((nf,), jnp.float32)}

    def apply(self, params, state, x, ctx):
        axes = tuple(range(x.ndim - 1))
        # stats in (at least) float32; promotes to f64 under gradient checks
        sdt = jnp.promote_types(jnp.float32, x.dtype)
        if ctx.train and not self.use_global_stats_in_train:
            xf = x.astype(sdt)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            new_state = {
                "mean": (self.decay * state["mean"]
                         + (1 - self.decay) * mean).astype(jnp.float32),
                "var": (self.decay * state["var"]
                        + (1 - self.decay) * var).astype(jnp.float32),
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = jnp.asarray(1.0, sdt) / jnp.sqrt(var.astype(sdt) + self.eps)
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
        if not self.lock_gamma_beta:
            y = y * params["gamma"] + params["beta"]
        return y, new_state


@register_serializable
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference: LocalResponseNormalization; cuDNN helper
    CudnnLocalResponseNormalizationHelper). NHWC: normalize along last axis."""
    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, state, x, ctx):
        half = self.n // 2
        sq = jnp.square(x)
        # Sum over a sliding window of channels via padding + cumulative trick.
        pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        sq_pad = jnp.pad(sq, pad)
        windows = [sq_pad[..., i:i + x.shape[-1]] for i in range(self.n)]
        ssum = sum(windows)
        denom = jnp.power(self.k + self.alpha * ssum, self.beta)
        return x / denom, state


@register_serializable
@dataclasses.dataclass(frozen=True)
class LayerNormalization(Layer):
    """Per-example normalization over the feature axis (no reference analog;
    needed for transformer parity — BERT import, TextGen models)."""
    eps: float = 1e-5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def initialize(self, key, input_type):
        nf = input_type.shape()[-1]
        dt = self.param_dtype()
        return {"gamma": jnp.ones((nf,), dt), "beta": jnp.zeros((nf,), dt)}

    def apply(self, params, state, x, ctx):
        xf = x.astype(jnp.promote_types(jnp.float32, x.dtype))
        # Single-pass moments: E[x²]−E[x]² puts both reductions directly
        # on xf, so XLA emits one multi-output fusion reading the
        # activation once.  jnp.var chains its reduction behind the mean,
        # which costs a second full read of xf (the 57 GB/s LayerNorm
        # fusions in the BERT step profile — PERF_ANALYSIS).  f32
        # accumulation keeps the cancellation benign for activations;
        # the max(·, 0) guards the roundoff-negative corner.
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.maximum(
            jnp.mean(xf * xf, axis=-1, keepdims=True) - mean * mean, 0.0)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y.astype(x.dtype)
        return y * params["gamma"] + params["beta"], state
