"""Recurrent layers.

Analogs of the reference's ``LSTM``, ``GravesLSTM`` (peepholes),
``GravesBidirectionalLSTM``/``Bidirectional`` wrapper, ``SimpleRnn``,
``LastTimeStep``, ``MaskZeroLayer`` (deeplearning4j-nn/.../nn/layers/
recurrent/, shared cell math in LSTMHelpers.java:58).

TPU-first design:
- Sequences are (N, T, F); the recurrence is a ``lax.scan`` over T with the
  (h, c) carry — compiler-friendly control flow, one compiled step body.
- The input projection x@Wx for ALL timesteps is hoisted out of the scan
  into a single (N*T, F)x(F, 4H) matmul that the MXU executes at full
  utilization; only the h@Wh recurrence stays sequential. This is the
  standard cuDNN-LSTM trick (the reference gets it via CudnnLSTMHelper),
  expressed in pure JAX.
- Masking follows the reference's semantics (SURVEY §5.7): masked timesteps
  emit zeros and do not advance the hidden state.
- Stateful streaming inference (``rnnTimeStep``) is supported by the model
  classes via an explicit carried-state API instead of hidden mutable state.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.inputs import InputType, FeedForwardType, RecurrentType
from deeplearning4j_tpu.nn.layers.base import FeedForwardLayer, Layer, LayerContext
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.utils.serde import register_serializable


def _apply_mask_step(mask_t, new_val, old_val):
    """Per-timestep mask: keep old where mask == 0."""
    m = mask_t[:, None].astype(new_val.dtype)
    return m * new_val + (1.0 - m) * old_val


@register_serializable
@dataclasses.dataclass(frozen=True)
class LSTM(FeedForwardLayer):
    """Standard LSTM (no peepholes). Gate order: [i, f, o, g] packed in one
    4H-wide projection. ``forget_gate_bias_init`` mirrors the reference's
    forgetGateBiasInit (LSTMHelpers defaults to 1.0 for gradient flow).

    ``gate_layout``: "gate_major" (default) packs the 4H columns as four
    H-wide gate blocks; "hidden_major" interleaves them per hidden unit
    (column h*4+g) so that a contiguous column tile holds ALL FOUR gates
    of a hidden-unit slice — the layout tensor parallelism needs to
    shard the recurrence over hidden units (the Wqkv head-major trick,
    applied to gates; parallel/tensor_parallel.py)."""
    activation: Activation = Activation.TANH
    gate_activation: Activation = Activation.SIGMOID
    forget_gate_bias_init: float = 1.0
    gate_layout: str = "gate_major"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(self.n_out, t)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        h = self.n_out
        kx, kh = jax.random.split(key)
        dt = self.param_dtype()
        b = jnp.zeros((4 * h,), dt)
        if self.gate_layout == "hidden_major":
            b = b.reshape(h, 4).at[:, 1].set(
                self.forget_gate_bias_init).reshape(4 * h)
        else:
            b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        return {
            "Wx": self.weight_init.init(kx, (n_in, 4 * h), n_in, h, dt),
            "Wh": self.weight_init.init(kh, (h, 4 * h), h, h, dt),
            "b": b,
        }

    def _gates(self, z):
        """Split the packed 4H projection into (i, f, o, g) per the
        configured column layout."""
        nh = self.n_out
        if self.gate_layout == "hidden_major":
            z4 = z.reshape(z.shape[0], nh, 4)
            return z4[..., 0], z4[..., 1], z4[..., 2], z4[..., 3]
        return (z[:, :nh], z[:, nh:2 * nh], z[:, 2 * nh:3 * nh],
                z[:, 3 * nh:])

    def _cell(self, params, carry, zx_t, mask_t):
        h_prev, c_prev = carry
        z = zx_t + h_prev @ params["Wh"]
        zi, zf, zo, zg = self._gates(z)
        i = self.gate_activation.apply(zi)
        f = self.gate_activation.apply(zf)
        o = self.gate_activation.apply(zo)
        g = self.activation.apply(zg)
        c = f * c_prev + i * g
        hy = o * self.activation.apply(c)
        if mask_t is not None:
            hy = _apply_mask_step(mask_t, hy, h_prev)
            c = _apply_mask_step(mask_t, c, c_prev)
        return (hy, c)

    def _fused_eligible(self) -> bool:
        """The fused Pallas recurrence implements exactly the default
        cell: gate-major [i|f|o|g] columns, sigmoid gates, tanh
        activation, no peepholes. Subclasses overriding ``_cell``
        (GravesLSTM) or non-default configs stay on the scan path."""
        return (type(self)._cell is LSTM._cell
                and self.gate_layout == "gate_major"
                and self.activation == Activation.TANH
                and self.gate_activation == Activation.SIGMOID)

    def apply(self, params, state, x, ctx, initial_state=None):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        n, t, _ = x.shape
        h = self.n_out
        # Hoisted input projection: one big MXU matmul over all timesteps.
        zx = jnp.einsum("nti,ig->ntg", x, params["Wx"]) + params["b"]
        if initial_state is None:
            h0 = jnp.zeros((n, h), x.dtype)
            c0 = jnp.zeros((n, h), x.dtype)
        else:
            h0, c0 = initial_state
        mask = ctx.mask

        # Helper tier (CudnnLSTMHelper analog): route the recurrence to
        # the fused Pallas kernel where the measured crossover (or an
        # explicit DL4J_LSTM_IMPL=fused) says it wins; any trace-time
        # kernel failure falls back silently to the scan below.
        if self._fused_eligible():
            from deeplearning4j_tpu.ops import pallas_lstm
            if pallas_lstm.choose_impl(n, h, t) == "fused":
                try:
                    ysT, hT, cT = pallas_lstm.lstm_fused(
                        zx.transpose(1, 0, 2), h0, c0, params["Wh"],
                        None if mask is None else mask.transpose(1, 0))
                    out = ysT.transpose(1, 0, 2)
                    if mask is not None:
                        out = out * mask[:, :, None].astype(out.dtype)
                    new_state = dict(state)
                    new_state["last_h"] = hT
                    new_state["last_c"] = cT
                    return out, new_state
                except Exception:
                    pass

        def step(carry, inp):
            if mask is None:
                zx_t = inp
                m_t = None
            else:
                zx_t, m_t = inp
            new_carry = self._cell(params, carry, zx_t, m_t)
            return new_carry, new_carry[0]

        xs = zx.transpose(1, 0, 2)
        inputs = xs if mask is None else (xs, mask.transpose(1, 0))
        (hT, cT), ys = lax.scan(step, (h0, c0), inputs)
        out = ys.transpose(1, 0, 2)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        new_state = dict(state)
        new_state["last_h"] = hT
        new_state["last_c"] = cT
        return out, new_state

    def step_one(self, params, x_t, carry):
        """Single-timestep streaming inference — the analog of the
        reference's ``rnnTimeStep`` (MultiLayerNetwork.java:2806)."""
        zx = x_t @ params["Wx"] + params["b"]
        return self._cell(params, carry, zx, None)


@register_serializable
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference: GravesLSTM, the A. Graves
    2013 formulation — peepholes from the cell state into i/f/o gates)."""

    def __post_init__(self):
        # fail at config time, not deep inside the first fit trace
        if self.gate_layout != "gate_major":
            raise ValueError(
                "GravesLSTM supports only gate_layout='gate_major'")

    def initialize(self, key, input_type):
        params = super().initialize(key, input_type)
        h = self.n_out
        dt = self.param_dtype()
        params["pI"] = jnp.zeros((h,), dt)
        params["pF"] = jnp.zeros((h,), dt)
        params["pO"] = jnp.zeros((h,), dt)
        return params

    def _cell(self, params, carry, zx_t, mask_t):
        h_prev, c_prev = carry
        nh = self.n_out
        z = zx_t + h_prev @ params["Wh"]
        i = self.gate_activation.apply(z[:, :nh] + params["pI"] * c_prev)
        f = self.gate_activation.apply(z[:, nh:2 * nh] + params["pF"] * c_prev)
        g = self.activation.apply(z[:, 3 * nh:])
        c = f * c_prev + i * g
        o = self.gate_activation.apply(z[:, 2 * nh:3 * nh] + params["pO"] * c)
        hy = o * self.activation.apply(c)
        if mask_t is not None:
            hy = _apply_mask_step(mask_t, hy, h_prev)
            c = _apply_mask_step(mask_t, c, c_prev)
        return (hy, c)


@register_serializable
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(FeedForwardLayer):
    """Bidirectional Graves LSTM as one layer (reference:
    GravesBidirectionalLSTM.java — independent fwd/bwd peephole cells,
    concatenated output). Composes Bidirectional(GravesLSTM) rather than
    subclassing LSTM so carry-based paths (TBPTT, rnn_time_step) don't
    mistake its {"fwd","bwd"} param/state structure for a plain cell."""
    activation: Activation = Activation.TANH
    gate_activation: Activation = Activation.SIGMOID
    forget_gate_bias_init: float = 1.0

    def _wrapper(self) -> "Bidirectional":
        inner = GravesLSTM(
            **{f.name: getattr(self, f.name)
               for f in dataclasses.fields(GravesLSTM)
               if hasattr(self, f.name)})
        return Bidirectional(fwd=inner, mode="concat", name=self.name)

    def output_type(self, input_type: InputType) -> InputType:
        return self._wrapper().output_type(input_type)

    def initialize(self, key, input_type):
        return self._wrapper().initialize(key, input_type)

    def init_state(self, input_type):
        return self._wrapper().init_state(input_type)

    def apply(self, params, state, x, ctx, initial_state=None):
        if initial_state is not None:
            raise ValueError(
                "GravesBidirectionalLSTM cannot carry state across chunks:"
                " the backward direction needs the full sequence")
        return self._wrapper().apply(params, state, x, ctx)


@register_serializable
@dataclasses.dataclass(frozen=True)
class SimpleRnn(FeedForwardLayer):
    """Vanilla RNN: h_t = act(x_t@Wx + h_{t-1}@Wh + b) (reference: SimpleRnn)."""
    activation: Activation = Activation.TANH

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(self.n_out, t)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        h = self.n_out
        kx, kh = jax.random.split(key)
        dt = self.param_dtype()
        return {
            "Wx": self.weight_init.init(kx, (n_in, h), n_in, h, dt),
            "Wh": self.weight_init.init(kh, (h, h), h, h, dt),
            "b": jnp.zeros((h,), dt),
        }

    def apply(self, params, state, x, ctx, initial_state=None):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        n, t, _ = x.shape
        zx = jnp.einsum("nti,ih->nth", x, params["Wx"]) + params["b"]
        h0 = (jnp.zeros((n, self.n_out), x.dtype) if initial_state is None
              else initial_state)
        mask = ctx.mask

        def step(h_prev, inp):
            if mask is None:
                zx_t, m_t = inp, None
            else:
                zx_t, m_t = inp
            h_new = self.activation.apply(zx_t + h_prev @ params["Wh"])
            if m_t is not None:
                h_new = _apply_mask_step(m_t, h_new, h_prev)
            return h_new, h_new

        xs = zx.transpose(1, 0, 2)
        inputs = xs if mask is None else (xs, mask.transpose(1, 0))
        hT, ys = lax.scan(step, h0, inputs)
        out = ys.transpose(1, 0, 2)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        new_state = dict(state)
        new_state["last_h"] = hT
        return out, new_state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Bidirectional(Layer):
    """Bidirectional wrapper (reference: nn/conf/layers/recurrent/
    Bidirectional.java with Mode ADD/MUL/AVERAGE/CONCAT)."""
    fwd: Optional[Layer] = None
    mode: str = "concat"  # concat|add|mul|average

    def __post_init__(self):
        if self.fwd is None:
            raise ValueError("Bidirectional requires an inner recurrent layer")

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.fwd.output_type(input_type)
        if self.mode == "concat":
            return RecurrentType(inner.size * 2, inner.timesteps)
        return inner

    def initialize(self, key, input_type):
        kf, kb = jax.random.split(key)
        return {"fwd": self.fwd.initialize(kf, input_type),
                "bwd": self.fwd.initialize(kb, input_type)}

    def init_state(self, input_type):
        return {"fwd": self.fwd.init_state(input_type),
                "bwd": self.fwd.init_state(input_type)}

    def apply(self, params, state, x, ctx):
        ctx_f, ctx_b = ctx, ctx
        if ctx.rng is not None:
            ctx_f, kb = ctx.split_rng()
            ctx_b = dataclasses.replace(ctx, rng=kb)
        yf, sf = self.fwd.apply(params["fwd"], state.get("fwd", {}), x, ctx_f)
        xr = jnp.flip(x, axis=1)
        mask_r = None if ctx.mask is None else jnp.flip(ctx.mask, axis=1)
        yb, sb = self.fwd.apply(params["bwd"], state.get("bwd", {}), xr,
                                dataclasses.replace(ctx_b, mask=mask_r))
        yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == "add":
            y = yf + yb
        elif self.mode == "mul":
            y = yf * yb
        elif self.mode == "average":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(self.mode)
        return y, {"fwd": sf, "bwd": sb}


@register_serializable
@dataclasses.dataclass(frozen=True)
class LastTimeStep(Layer):
    """Wraps a recurrent layer, emitting only the last (unmasked) timestep
    (reference: nn/conf/layers/recurrent/LastTimeStep.java)."""
    inner: Optional[Layer] = None

    def output_type(self, input_type: InputType) -> InputType:
        rt = self.inner.output_type(input_type)
        return FeedForwardType(rt.size)

    def initialize(self, key, input_type):
        return self.inner.initialize(key, input_type)

    def init_state(self, input_type):
        return self.inner.init_state(input_type)

    def apply(self, params, state, x, ctx, initial_state=None):
        if initial_state is not None:
            y, new_state = self.inner.apply(params, state, x, ctx,
                                            initial_state=initial_state)
        else:
            y, new_state = self.inner.apply(params, state, x, ctx)
        if ctx.mask is not None:
            # last unmasked index per example
            idx = jnp.sum(ctx.mask.astype(jnp.int32), axis=1) - 1
            idx = jnp.clip(idx, 0, y.shape[1] - 1)
            out = jnp.take_along_axis(y, idx[:, None, None].repeat(y.shape[-1], -1),
                                      axis=1)[:, 0]
        else:
            out = y[:, -1]
        return out, new_state


@register_serializable
@dataclasses.dataclass(frozen=True)
class MaskZeroLayer(Layer):
    """Sets the mask from a sentinel input value (reference:
    nn/conf/layers/util/MaskZeroLayer.java)."""
    inner: Optional[Layer] = None
    mask_value: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        return self.inner.output_type(input_type)

    def initialize(self, key, input_type):
        return self.inner.initialize(key, input_type)

    def init_state(self, input_type):
        return self.inner.init_state(input_type)

    def apply(self, params, state, x, ctx, initial_state=None):
        mask = jnp.any(x != self.mask_value, axis=-1).astype(jnp.float32)
        ctx = dataclasses.replace(ctx, mask=mask)
        if initial_state is not None:
            return self.inner.apply(params, state, x, ctx,
                                    initial_state=initial_state)
        return self.inner.apply(params, state, x, ctx)


def unwrap_recurrent(layer):
    """The stateful core of a layer: LastTimeStep/MaskZeroLayer delegate
    params, state and (since round 4) ``initial_state`` to their inner
    layer, so TBPTT carries and rnn_time_step must look through them."""
    inner = getattr(layer, "inner", None)
    if isinstance(layer, (LastTimeStep, MaskZeroLayer)) \
            and inner is not None:
        return unwrap_recurrent(inner)
    return layer


def first_bidirectional_name(named_layers):
    """Name of the first layer whose (unwrapped) core is bidirectional,
    or None. Shared by rnn_time_step's hard check and TBPTT's warning on
    both model types, so the wrapper list stays in lockstep (advisor
    r4). ``named_layers`` yields (name, layer) pairs."""
    for name, layer in named_layers:
        if isinstance(unwrap_recurrent(layer),
                      (Bidirectional, GravesBidirectionalLSTM)):
            return name
    return None


def warn_tbptt_bidirectional(name: str, stacklevel: int = 4):
    """TBPTT chunks a bidirectional layer with no carried state: each
    chunk's backward pass is truncated at the chunk boundary, which
    silently differs from full-sequence BPTT (advisor r4)."""
    import warnings
    warnings.warn(
        f"TBPTT fit with bidirectional layer '{name}': bidirectional "
        "cores carry no state across chunks, so the backward pass is "
        "truncated at each chunk boundary (differs from full-sequence "
        "BPTT). Use backprop_type='standard' for exact bidirectional "
        "gradients.", UserWarning, stacklevel=stacklevel)
