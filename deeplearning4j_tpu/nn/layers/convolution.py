"""Convolutional layer family (NHWC, TPU-native).

Analogs of the reference's conv stack: ``ConvolutionLayer``
(deeplearning4j-nn/.../nn/layers/convolution/ConvolutionLayer.java:57 — which
hooks cuDNN reflectively at :75-85), ``SeparableConvolution2D``,
``Deconvolution2D``, ``SubsamplingLayer`` (max/avg pool), ``Upsampling2D``,
``ZeroPaddingLayer``, ``Cropping2D``, ``SpaceToDepthLayer``,
``SpaceToBatchLayer``, ``Convolution1DLayer``.

TPU-first design notes:
- All activations are NHWC and all kernels HWIO — the layouts XLA's TPU
  conv emitter maps directly onto the MXU without relayout copies. There is
  no cuDNN-helper indirection: ``lax.conv_general_dilated`` IS the
  accelerated path, and XLA fuses bias+activation into the conv epilogue.
- ``ConvolutionMode`` mirrors the reference enum (Strict/Truncate/Same):
  Same → XLA 'SAME' padding; Truncate/Strict → 'VALID' with Strict
  additionally validating divisibility at config time.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.inputs import (
    ConvolutionalFlatType,
    ConvolutionalType,
    InputType,
    RecurrentType,
)
from deeplearning4j_tpu.nn.layers.base import FeedForwardLayer, Layer, LayerContext
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.utils.serde import register_enum, register_serializable

DIMENSION_NUMBERS = ("NHWC", "HWIO", "NHWC")


@register_enum
class ConvolutionMode(enum.Enum):
    STRICT = "strict"
    TRUNCATE = "truncate"
    SAME = "same"


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_dim(size: int, k: int, s: int, d: int, mode: ConvolutionMode,
             pad: int) -> int:
    eff_k = (k - 1) * d + 1
    if mode is ConvolutionMode.SAME:
        return -(-size // s)  # ceil
    out = (size + 2 * pad - eff_k) // s + 1
    if mode is ConvolutionMode.STRICT and (size + 2 * pad - eff_k) % s != 0:
        raise ValueError(
            f"ConvolutionMode.STRICT: (size={size} + 2*pad={pad} - k_eff={eff_k})"
            f" not divisible by stride={s}; use TRUNCATE or SAME"
        )
    return out


def _padding_arg(mode: ConvolutionMode, pad: Tuple[int, int]):
    if mode is ConvolutionMode.SAME:
        return "SAME"
    return [(pad[0], pad[0]), (pad[1], pad[1])]


def _ensure_nhwc(x: jnp.ndarray, input_type: InputType) -> jnp.ndarray:
    if isinstance(input_type, ConvolutionalFlatType):
        n = x.shape[0]
        return x.reshape(n, input_type.height, input_type.width, input_type.channels)
    return x


@register_serializable
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(FeedForwardLayer):
    """2D convolution. Reference: nn/conf/layers/ConvolutionLayer +
    nn/layers/convolution/ConvolutionLayer.java (im2col or cuDNN); here a
    single ``lax.conv_general_dilated`` that XLA tiles onto the MXU."""
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    groups: int = 1

    def _resolve_in(self, input_type: InputType) -> ConvolutionalType:
        if isinstance(input_type, ConvolutionalFlatType):
            input_type = input_type.unflatten()
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(f"{type(self).__name__} needs convolutional input,"
                             f" got {input_type}")
        return input_type

    def output_type(self, input_type: InputType) -> InputType:
        it = self._resolve_in(input_type)
        k, s, d, p = map(_pair, (self.kernel_size, self.stride, self.dilation,
                                 self.padding))
        h = _out_dim(it.height, k[0], s[0], d[0], self.convolution_mode, p[0])
        w = _out_dim(it.width, k[1], s[1], d[1], self.convolution_mode, p[1])
        return ConvolutionalType(h, w, self.n_out)

    def initialize(self, key, input_type):
        it = self._resolve_in(input_type)
        k = _pair(self.kernel_size)
        c_in = it.channels
        # Each output unit only sees c_in/groups input channels.
        fan_in = (c_in // self.groups) * k[0] * k[1]
        fan_out = (self.n_out // self.groups) * k[0] * k[1]
        dt = self.param_dtype()
        kw, _ = jax.random.split(key)
        params = {"W": self.weight_init.init(
            kw, (k[0], k[1], c_in // self.groups, self.n_out), fan_in, fan_out, dt)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dt)
        return params

    def apply(self, params, state, x, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        s, d, p = map(_pair, (self.stride, self.dilation, self.padding))
        # bf16 convs: XLA accumulates on the MXU in f32 already, and an
        # explicit preferred_element_type=f32 here breaks the transpose
        # (f32 cotangent meets bf16 operands in grad-of-conv)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=s,
            padding=_padding_arg(self.convolution_mode, p),
            rhs_dilation=d, dimension_numbers=DIMENSION_NUMBERS,
            feature_group_count=self.groups,
        )
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class SeparableConvolution2D(ConvolutionLayer):
    """Depthwise + pointwise conv (reference: SeparableConvolution2D)."""
    depth_multiplier: int = 1

    def initialize(self, key, input_type):
        it = self._resolve_in(input_type)
        k = _pair(self.kernel_size)
        c_in = it.channels
        dm = self.depth_multiplier
        kd, kp = jax.random.split(key)
        dt = self.param_dtype()
        params = {
            # depthwise kernel: HWIO with feature_group_count = c_in
            "dW": self.weight_init.init(kd, (k[0], k[1], 1, c_in * dm),
                                        k[0] * k[1], dm, dt),
            "pW": self.weight_init.init(kp, (1, 1, c_in * dm, self.n_out),
                                        c_in * dm, self.n_out, dt),
        }
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dt)
        return params

    def apply(self, params, state, x, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        s, d, p = map(_pair, (self.stride, self.dilation, self.padding))
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["dW"], window_strides=s,
            padding=_padding_arg(self.convolution_mode, p),
            rhs_dilation=d, dimension_numbers=DIMENSION_NUMBERS,
            feature_group_count=c_in)
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=DIMENSION_NUMBERS)
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (reference: Deconvolution2D)."""

    def output_type(self, input_type: InputType) -> InputType:
        it = self._resolve_in(input_type)
        k, s, d, p = map(_pair, (self.kernel_size, self.stride, self.dilation,
                                 self.padding))
        if self.convolution_mode is ConvolutionMode.SAME:
            h = it.height * s[0]
            w = it.width * s[1]
        else:
            eff_kh = (k[0] - 1) * d[0] + 1
            eff_kw = (k[1] - 1) * d[1] + 1
            h = s[0] * (it.height - 1) + eff_kh - 2 * p[0]
            w = s[1] * (it.width - 1) + eff_kw - 2 * p[1]
        return ConvolutionalType(h, w, self.n_out)

    def apply(self, params, state, x, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        s, d, p = map(_pair, (self.stride, self.dilation, self.padding))
        k = _pair(self.kernel_size)
        # Transposed conv as input-dilated conv: out = s*(in-1) + k_eff - 2p.
        # (lax.conv_transpose's padding convention differs; explicit
        # lhs_dilation keeps the arithmetic identical to the reference's
        # Deconvolution2D output-shape formula.)
        pads = []
        for ax in (0, 1):
            k_eff = (k[ax] - 1) * d[ax] + 1
            if self.convolution_mode is ConvolutionMode.SAME:
                total = s[ax] + k_eff - 2   # makes out = in * s
                lo = total // 2
                pads.append((lo, total - lo))
            else:
                pads.append((k_eff - 1 - p[ax], k_eff - 1 - p[ax]))
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(1, 1), padding=pads,
            lhs_dilation=s, rhs_dilation=d,
            dimension_numbers=DIMENSION_NUMBERS)
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_enum
class PoolingType(enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@register_serializable
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """Spatial pooling (reference: SubsamplingLayer; cuDNN helper at
    deeplearning4j-cuda/.../CudnnSubsamplingHelper.java). On TPU this is a
    ``lax.reduce_window`` which XLA fuses aggressively."""
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    pooling_type: PoolingType = PoolingType.MAX
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, ConvolutionalFlatType):
            input_type = input_type.unflatten()
        it = input_type
        k, s, p = map(_pair, (self.kernel_size, self.stride, self.padding))
        h = _out_dim(it.height, k[0], s[0], 1, self.convolution_mode, p[0])
        w = _out_dim(it.width, k[1], s[1], 1, self.convolution_mode, p[1])
        return ConvolutionalType(h, w, it.channels)

    def apply(self, params, state, x, ctx):
        k, s, p = map(_pair, (self.kernel_size, self.stride, self.padding))
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            pad = [(0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0)]
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        if self.pooling_type is PoolingType.MAX:
            return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad), state
        if self.pooling_type is PoolingType.SUM:
            return lax.reduce_window(x, 0.0, lax.add, window, strides, pad), state
        if self.pooling_type is PoolingType.AVG:
            summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            if pad == "SAME":
                counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                           window, strides, pad)
                return summed / counts, state
            return summed / (k[0] * k[1]), state
        if self.pooling_type is PoolingType.PNORM:
            pn = float(self.pnorm)
            summed = lax.reduce_window(jnp.abs(x) ** pn, 0.0, lax.add, window,
                                       strides, pad)
            return summed ** (1.0 / pn), state
        raise ValueError(self.pooling_type)


@register_serializable
@dataclasses.dataclass(frozen=True)
class Upsampling2D(Layer):
    """Nearest-neighbor upsampling (reference: Upsampling2D)."""
    size: Tuple[int, int] = (2, 2)

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        it = input_type
        s = _pair(self.size)
        return ConvolutionalType(it.height * s[0], it.width * s[1], it.channels)

    def apply(self, params, state, x, ctx):
        s = _pair(self.size)
        x = jnp.repeat(x, s[0], axis=1)
        x = jnp.repeat(x, s[1], axis=2)
        return x, state


@register_serializable
@dataclasses.dataclass(frozen=True)
class ZeroPaddingLayer(Layer):
    """Zero padding (reference: ZeroPaddingLayer). padding = (top, bottom,
    left, right)."""
    pad: Tuple[int, int, int, int] = (0, 0, 0, 0)

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        it = input_type
        t, b, l, r = self.pad
        return ConvolutionalType(it.height + t + b, it.width + l + r, it.channels)

    def apply(self, params, state, x, ctx):
        t, b, l, r = self.pad
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Cropping2D(Layer):
    """Spatial cropping (reference: nn/conf/layers/convolutional/Cropping2D)."""
    crop: Tuple[int, int, int, int] = (0, 0, 0, 0)

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        it = input_type
        t, b, l, r = self.crop
        return ConvolutionalType(it.height - t - b, it.width - l - r, it.channels)

    def apply(self, params, state, x, ctx):
        t, b, l, r = self.crop
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b if b else h, l:w - r if r else w, :], state


@register_serializable
@dataclasses.dataclass(frozen=True)
class SpaceToDepthLayer(Layer):
    """(reference: SpaceToDepthLayer). NHWC space-to-depth, block rearrange."""
    block_size: int = 2

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        it = input_type
        b = self.block_size
        return ConvolutionalType(it.height // b, it.width // b, it.channels * b * b)

    def apply(self, params, state, x, ctx):
        n, h, w, c = x.shape
        b = self.block_size
        x = x.reshape(n, h // b, b, w // b, b, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h // b, w // b, b * b * c), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class SpaceToBatchLayer(Layer):
    """(reference: SpaceToBatchLayer). Moves spatial blocks into batch dim."""
    block_size: Tuple[int, int] = (2, 2)

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        it = input_type
        bh, bw = _pair(self.block_size)
        return ConvolutionalType(it.height // bh, it.width // bw, it.channels)

    def apply(self, params, state, x, ctx):
        n, h, w, c = x.shape
        bh, bw = _pair(self.block_size)
        x = x.reshape(n, h // bh, bh, w // bw, bw, c)
        x = x.transpose(2, 4, 0, 1, 3, 5)
        return x.reshape(n * bh * bw, h // bh, w // bw, c), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Convolution1DLayer(FeedForwardLayer):
    """1D (temporal) convolution over (N, T, F) sequences (reference:
    Convolution1DLayer)."""
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: ConvolutionMode = ConvolutionMode.SAME

    def output_type(self, input_type: InputType) -> InputType:
        if not isinstance(input_type, RecurrentType):
            raise ValueError("Convolution1DLayer needs recurrent input")
        t = input_type.timesteps
        if t is not None and t > 0:
            t = _out_dim(t, self.kernel_size, self.stride, self.dilation,
                         self.convolution_mode, self.padding)
        return RecurrentType(self.n_out, t)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        fan_in = n_in * self.kernel_size
        dt = self.param_dtype()
        params = {"W": self.weight_init.init(
            key, (self.kernel_size, n_in, self.n_out), fan_in,
            self.n_out * self.kernel_size, dt)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dt)
        return params

    def apply(self, params, state, x, ctx):
        pad = ("SAME" if self.convolution_mode is ConvolutionMode.SAME
               else [(self.padding, self.padding)])
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NHC", "HIO", "NHC"))
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Subsampling1DLayer(Layer):
    """1D pooling over (N, T, F) sequences (reference:
    Subsampling1DLayer)."""
    kernel_size: int = 2
    stride: int = 2
    pooling_type: PoolingType = PoolingType.MAX

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None and t > 0:
            t = (t - self.kernel_size) // self.stride + 1
        return RecurrentType(input_type.size, t)

    def apply(self, params, state, x, ctx):
        if self.pooling_type is PoolingType.MAX:
            init, fn = -jnp.inf, lax.max
        else:
            init, fn = 0.0, lax.add
        y = lax.reduce_window(x, init, fn,
                              (1, self.kernel_size, 1),
                              (1, self.stride, 1), "VALID")
        if self.pooling_type is PoolingType.AVG:
            y = y / self.kernel_size
        return y, state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Upsampling1D(Layer):
    """Temporal repeat upsampling (reference: Upsampling1D)."""
    size: int = 2

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return RecurrentType(input_type.size,
                             None if t in (None, -1) else t * self.size)

    def apply(self, params, state, x, ctx):
        return jnp.repeat(x, self.size, axis=1), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class ZeroPadding1DLayer(Layer):
    """Temporal zero padding (reference: ZeroPadding1DLayer)."""
    pad: Tuple[int, int] = (0, 0)

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return RecurrentType(input_type.size,
                             None if t in (None, -1)
                             else t + self.pad[0] + self.pad[1])

    def apply(self, params, state, x, ctx):
        return jnp.pad(x, ((0, 0), self.pad, (0, 0))), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class Cropping1D(Layer):
    """Temporal cropping (reference: convolutional/Cropping1D)."""
    crop: Tuple[int, int] = (0, 0)

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return RecurrentType(input_type.size,
                             None if t in (None, -1)
                             else t - self.crop[0] - self.crop[1])

    def apply(self, params, state, x, ctx):
        lo, hi = self.crop
        end = x.shape[1] - hi
        return x[:, lo:end, :], state
