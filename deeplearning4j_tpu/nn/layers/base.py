"""Layer API.

Analog of the reference's layer contract (deeplearning4j-nn/.../nn/api/
Layer.java:38 — ``activate``/``backpropGradient`` pairs) redesigned for a
functional autodiff core: a layer is a **serializable config** with

- ``output_type(input_type)``    shape inference (drives auto-preprocessors),
- ``initialize(key, input_type)``→ parameter pytree (dict of arrays),
- ``init_state(input_type)``     → non-trainable state (e.g. BN running stats),
- ``apply(params, state, x, ctx)``→ ``(y, new_state)`` — a pure function.

There is **no** backprop method anywhere: gradients come from ``jax.grad``
through ``apply``. Layers must therefore be trace-safe: no data-dependent
Python control flow, static shapes only.

``LayerContext`` carries train/eval mode, a PRNG key for stochastic layers
(dropout, VAE sampling), and optional input masks (SURVEY §5.7).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.optimize.updaters import Updater

Params = Dict[str, Any]
State = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LayerContext:
    train: bool = False
    rng: Optional[jax.Array] = None
    mask: Optional[jnp.ndarray] = None    # (N, T) for sequence data

    def split_rng(self) -> Tuple["LayerContext", Optional[jax.Array]]:
        if self.rng is None:
            return self, None
        k1, k2 = jax.random.split(self.rng)
        return dataclasses.replace(self, rng=k1), k2


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base config for all layers. Field defaults here mirror the knobs
    every DL4J layer config inherits from ``BaseLayer`` (activation, weight
    init, L1/L2, dropout, per-layer updater override, frozen flag)."""

    name: Optional[str] = None
    # float drop-probability, or an nn.dropout.IDropout instance
    # (Dropout/AlphaDropout/GaussianDropout/GaussianNoise)
    dropout: Any = 0.0            # applied to the layer INPUT during training
    l1: float = 0.0
    l2: float = 0.0
    updater: Optional[Updater] = None   # per-layer override; None = global
    frozen: bool = False
    dtype: Optional[str] = None   # param dtype override ("float32"/"bfloat16")
    weight_noise: Optional[Any] = None  # nn.weightnoise.IWeightNoise
    constraints: Tuple = ()             # nn.constraints.LayerConstraint s

    # ---- contract -------------------------------------------------------
    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def initialize(self, key: jax.Array, input_type: InputType) -> Params:
        return {}

    def init_state(self, input_type: InputType) -> State:
        return {}

    def apply(self, params: Params, state: State, x: jnp.ndarray,
              ctx: LayerContext) -> Tuple[jnp.ndarray, State]:
        raise NotImplementedError

    # ---- helpers --------------------------------------------------------
    @property
    def has_params(self) -> bool:
        return True

    def regularization_loss(self, params: Params) -> jnp.ndarray:
        """L1/L2 penalty over this layer's weight-like params (DL4J applies
        l1/l2 to weights only, not biases — param key convention: keys
        starting with 'b' / 'beta' / 'mean' / 'var' are exempt)."""
        if (self.l1 == 0.0 and self.l2 == 0.0) or not params:
            return jnp.zeros((), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        # Check the LEAF-level key (last path component), so nested wrapper
        # params ({"fwd": {...,"b":...}, "bwd": {...}}) are classified per
        # actual parameter, not per wrapper key.
        from deeplearning4j_tpu.nn.param_keys import is_weight_path
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            if not is_weight_path(path):
                continue
            if self.l1:
                total = total + self.l1 * jnp.sum(jnp.abs(leaf))
            if self.l2:
                total = total + 0.5 * self.l2 * jnp.sum(jnp.square(leaf))
        return total

    def maybe_dropout(self, x: jnp.ndarray, ctx: LayerContext,
                      key: Optional[jax.Array]) -> jnp.ndarray:
        """Input dropout (inverted scaling, matching the reference's
        ``Dropout`` with p = retain probability semantics inverted: here
        ``dropout`` is the DROP probability, the common modern convention).
        Also accepts any IDropout (Alpha/Gaussian...; nn/dropout.py)."""
        if not ctx.train or key is None:
            return x
        if isinstance(self.dropout, (int, float)):
            if self.dropout <= 0.0:
                return x
            from deeplearning4j_tpu.nn.dropout import Dropout
            return Dropout(float(self.dropout)).apply_dropout(x, key)
        return self.dropout.apply_dropout(x, key)

    def apply_weight_noise(self, params, ctx: LayerContext,
                           key: Optional[jax.Array]):
        """Perturb params for this forward pass when a weight-noise conf is
        set (reference: conf/weightnoise/, applied in BaseLayer
        .getParamWithNoise)."""
        if self.weight_noise is None or not ctx.train or key is None \
                or not params:
            return params
        return self.weight_noise.apply_noise(params, key)

    def param_dtype(self, default=jnp.float32):
        if self.dtype == "bfloat16":
            return jnp.bfloat16
        if self.dtype == "float32" or self.dtype is None:
            return default
        return jnp.dtype(self.dtype)


@dataclasses.dataclass(frozen=True)
class FeedForwardLayer(Layer):
    """Base for layers with explicit nIn/nOut, matching the reference's
    ``FeedForwardLayer`` config. ``n_in`` may be None — inferred from the
    incoming ``InputType`` like DL4J's ``setNIn`` override mechanism."""
    n_in: Optional[int] = None
    n_out: int = 0
    activation: Activation = Activation.IDENTITY
    weight_init: WeightInit = WeightInit.XAVIER
    has_bias: bool = True

    def resolved_n_in(self, input_type: InputType) -> int:
        if self.n_in is not None:
            return self.n_in
        shape = input_type.shape()
        return shape[-1]
