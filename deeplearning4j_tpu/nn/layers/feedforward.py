"""Dense / embedding / elementwise feed-forward layers.

Analogs of the reference's ``nn/conf/layers/DenseLayer``, ``EmbeddingLayer``,
``EmbeddingSequenceLayer``, ``ActivationLayer``, ``DropoutLayer``,
``AutoEncoder`` (deeplearning4j-nn/.../nn/layers/feedforward/). Forward math
only; backward is ``jax.grad``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import (
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from deeplearning4j_tpu.nn.layers.base import FeedForwardLayer, Layer, LayerContext
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class DenseLayer(FeedForwardLayer):
    """y = act(x @ W + b). W: (n_in, n_out) so the matmul hits the MXU with
    the feature axis on lanes; works on (N, F) and (N, T, F) inputs alike."""

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return RecurrentType(self.n_out, input_type.timesteps)
        return FeedForwardType(self.n_out)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        kw, _ = jax.random.split(key)
        dt = self.param_dtype()
        params = {"W": self.weight_init.init(kw, (n_in, self.n_out), n_in,
                                             self.n_out, dt)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dt)
        return params

    def apply(self, params, state, x, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        y = jnp.einsum("...i,io->...o", x, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(FeedForwardLayer):
    """Integer-index lookup (reference: EmbeddingLayer — a Dense layer whose
    input is an index; forward is a gather, backward a scatter-add, both of
    which XLA lowers to efficient dynamic-slice/segment ops on TPU)."""

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(self.n_out)

    def initialize(self, key, input_type):
        n_in = self.n_in
        if n_in is None:
            raise ValueError("EmbeddingLayer requires explicit n_in (vocab size)")
        dt = self.param_dtype()
        params = {"W": self.weight_init.init(key, (n_in, self.n_out), n_in,
                                             self.n_out, dt)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dt)
        return params

    def apply(self, params, state, x, ctx):
        idx = x.astype(jnp.int32)
        if idx.ndim > 1 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class EmbeddingSequenceLayer(FeedForwardLayer):
    """Sequence of indices (N, T) → (N, T, n_out) (reference:
    EmbeddingSequenceLayer)."""

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(self.n_out, t)

    def initialize(self, key, input_type):
        if self.n_in is None:
            raise ValueError("EmbeddingSequenceLayer requires explicit n_in")
        dt = self.param_dtype()
        return {"W": self.weight_init.init(key, (self.n_in, self.n_out),
                                           self.n_in, self.n_out, dt)}

    def apply(self, params, state, x, ctx):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        return jnp.take(params["W"], idx, axis=0), state


def _type_for_trailing(shape):
    """Trailing (non-batch) dims → InputType, same family mapping as
    ReshapeVertex (1 → FF, 2 → (T, F) recurrent, 3 → NHWC conv)."""
    if len(shape) == 1:
        return FeedForwardType(shape[0])
    if len(shape) == 2:
        return RecurrentType(shape[1], shape[0])
    if len(shape) == 3:
        return ConvolutionalType(shape[0], shape[1], shape[2])
    raise ValueError(f"unsupported shape arity: {shape}")


@register_serializable
@dataclasses.dataclass(frozen=True)
class ReshapeLayer(Layer):
    """Reshape the trailing (non-batch) dims to ``shape``; one -1 allowed.

    Row-major (C-order) element order, matching Keras ``Reshape`` — the
    reference materializes that layer's ``target_shape`` via a dedicated
    preprocessor (KerasReshape.java:40,67); here it is a first-class
    shape-only layer."""
    shape: tuple = ()

    @property
    def has_params(self):
        return False

    def resolved_shape(self, input_type: InputType):
        total = 1
        for d in input_type.shape():
            if d < 0:
                raise ValueError(
                    "ReshapeLayer needs a fully-known input shape; got "
                    f"{input_type.shape()} (unknown timesteps)")
            total *= d
        s = [int(v) for v in self.shape]
        if s.count(-1) > 1:
            raise ValueError(f"ReshapeLayer shape {s} has multiple -1s")
        known = 1
        for v in s:
            if v != -1:
                known *= v
        if -1 in s:
            if known == 0 or total % known:
                raise ValueError(
                    f"cannot infer -1 in reshape {s} from {total} elements")
            s[s.index(-1)] = total // known
        elif known != total:
            raise ValueError(
                f"reshape {tuple(s)} incompatible with input "
                f"{input_type.shape()} ({total} elements)")
        return tuple(s)

    def output_type(self, input_type: InputType) -> InputType:
        return _type_for_trailing(self.resolved_shape(input_type))

    def apply(self, params, state, x, ctx):
        s = [int(v) for v in self.shape]
        return x.reshape((x.shape[0],) + tuple(s)), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class PermuteLayer(Layer):
    """Transpose the trailing (non-batch) dims by 1-indexed ``dims``
    (Keras ``Permute`` convention: dims=(2, 1) swaps the first two
    non-batch axes). The reference silently lacks this — KerasReshape.java
    is its closest relative; we implement the real transpose."""
    dims: tuple = ()

    @property
    def has_params(self):
        return False

    def _perm(self, rank: int):
        dims = tuple(int(d) for d in self.dims)
        if sorted(dims) != list(range(1, rank + 1)):
            raise ValueError(
                f"PermuteLayer dims {dims} is not a permutation of "
                f"1..{rank}")
        return dims

    def output_type(self, input_type: InputType) -> InputType:
        shape = input_type.shape()
        if any(d < 0 for d in shape):
            raise ValueError(
                "PermuteLayer needs a fully-known input shape; got "
                f"{shape} (unknown timesteps)")
        dims = self._perm(len(shape))
        return _type_for_trailing(tuple(shape[d - 1] for d in dims))

    def apply(self, params, state, x, ctx):
        dims = self._perm(x.ndim - 1)
        return x.transpose((0,) + dims), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class ElementWiseMultiplicationLayer(FeedForwardLayer):
    """out = act(x ⊙ w + b) with a learnable per-feature weight vector
    (reference: nn/conf/layers/misc/ElementWiseMultiplicationLayer.java +
    nn/layers/feedforward/elementwise/ElementWiseMultiplicationLayer.java
    — input and output sizes are equal; the configured weight init draws
    the vector with the layer's fan-in/fan-out, matching
    ElementWiseParamInitializer)."""

    def __post_init__(self):
        if self.n_in is not None and self.n_out and self.n_in != self.n_out:
            raise ValueError(
                "ElementWiseMultiplicationLayer must have the same input "
                f"and output size. Got n_in={self.n_in}, n_out={self.n_out}")

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return RecurrentType(self.resolved_n_out(input_type),
                                 input_type.timesteps)
        return FeedForwardType(self.resolved_n_out(input_type))

    def resolved_n_out(self, input_type):
        return self.n_out or self.resolved_n_in(input_type)

    def initialize(self, key, input_type):
        n = self.resolved_n_in(input_type)
        if self.n_out and self.n_out != n:
            raise ValueError(
                "ElementWiseMultiplicationLayer must have the same input "
                f"and output size. Got n_in={n}, n_out={self.n_out}")
        dt = self.param_dtype()
        params = {"W": self.weight_init.init(key, (n,), n, n, dt)}
        if self.has_bias:
            params["b"] = jnp.zeros((n,), dt)
        return params

    def apply(self, params, state, x, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        y = x * params["W"]
        if self.has_bias:
            y = y + params["b"]
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """Standalone activation (reference: nn/conf/layers/ActivationLayer).
    ``alpha`` parameterizes LEAKYRELU (negative slope; the reference's
    ActivationLReLU(alpha)) and ELU — None keeps each function's
    default (leaky 0.01, elu 1.0)."""
    activation: Activation = Activation.RELU
    alpha: Optional[float] = None

    @property
    def has_params(self):
        return False

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, ctx):
        if self.alpha is not None:
            if self.activation == Activation.LEAKYRELU:
                return jax.nn.leaky_relu(x, self.alpha), state
            if self.activation == Activation.ELU:
                return jax.nn.elu(x, self.alpha), state
        return self.activation.apply(x), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class DropoutLayer(Layer):
    """Standalone dropout layer (reference: nn/conf/layers/DropoutLayer).
    ``dropout`` field from the base config is the drop probability."""
    dropout: float = 0.5

    @property
    def has_params(self):
        return False

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, ctx):
        ctx, dk = ctx.split_rng()
        return self.maybe_dropout(x, ctx, dk), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder layer (reference: nn/layers/feedforward/
    autoencoder/AutoEncoder.java). In a feed-forward stack it behaves as a
    dense encoder; ``reconstruct``/pretraining uses the tied decoder params.
    """
    corruption_level: float = 0.3

    def output_type(self, input_type):
        return FeedForwardType(self.n_out)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        kw, kv = jax.random.split(key)
        dt = self.param_dtype()
        return {
            "W": self.weight_init.init(kw, (n_in, self.n_out), n_in, self.n_out, dt),
            "b": jnp.zeros((self.n_out,), dt),
            "vb": jnp.zeros((n_in,), dt),   # visible bias for reconstruction
        }

    def apply(self, params, state, x, ctx):
        y = jnp.einsum("...i,io->...o", x, params["W"]) + params["b"]
        return self.activation.apply(y), state

    def reconstruct(self, params, h):
        v = jnp.einsum("...o,io->...i", h, params["W"]) + params["vb"]
        return self.activation.apply(v)

    @property
    def supports_pretrain(self) -> bool:
        return True

    def pretrain_loss(self, params, x, key) -> jnp.ndarray:
        """Denoising-reconstruction loss (reference: AutoEncoder
        .computeGradientAndScore — corrupt, encode, decode, squared
        error)."""
        if self.corruption_level > 0.0 and key is not None:
            keep = jax.random.bernoulli(key, 1.0 - self.corruption_level,
                                        x.shape)
            xc = jnp.where(keep, x, 0.0)
        else:
            xc = x
        h = self.activation.apply(
            jnp.einsum("...i,io->...o", xc, params["W"]) + params["b"])
        v = self.activation.apply(
            jnp.einsum("...o,io->...i", h, params["W"]) + params["vb"])
        return jnp.mean(jnp.sum(jnp.square(x - v), axis=-1))


@register_serializable
@dataclasses.dataclass(frozen=True)
class MixtureOfExperts(FeedForwardLayer):
    """Sparse MoE FFN (no reference analog — SURVEY §2.11 row 7 lists
    expert parallelism as ABSENT there; designed fresh per §7.2 stage 7).
    Top-k routed expert FFNs over the feature dim; expert weights are
    stacked (E, ...) so ``parallel.moe.set_default_mesh`` shards them over
    the ``expert`` mesh axis and GSPMD inserts the dispatch all-to-alls.
    The load-balancing + router-z losses are surfaced through layer state
    (``moe_aux_loss``) and added to the training loss by the models."""

    num_experts: int = 4
    hidden: int = 0              # d_ff; 0 → 4 * n_out
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    z_weight: float = 0.001

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return RecurrentType(self.n_out, input_type.timesteps)
        return FeedForwardType(self.n_out)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        d_ff = self.hidden or 4 * self.n_out
        dt = self.param_dtype()
        kg, k1, k2 = jax.random.split(key, 3)
        e = self.num_experts
        return {
            "gate": self.weight_init.init(kg, (n_in, e), n_in, e, dt),
            "w_in": self.weight_init.init(k1, (e, n_in, d_ff), n_in, d_ff, dt),
            "b_in": jnp.zeros((e, d_ff), dt),
            "w_out": self.weight_init.init(k2, (e, d_ff, self.n_out), d_ff,
                                           self.n_out, dt),
            "b_out": jnp.zeros((e, self.n_out), dt),
        }

    def init_state(self, input_type):
        return {"moe_aux_loss": jnp.zeros((), jnp.float32)}

    def apply(self, params, state, x, ctx):
        from deeplearning4j_tpu.parallel.moe import moe_ffn
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        # (N, T) padding mask for sequence inputs: padded tokens are not
        # routed, consume no capacity, and don't skew the aux loss
        tmask = ctx.mask if (ctx.mask is not None and x.ndim == 3) else None
        out = moe_ffn(x, params["gate"], params["w_in"], params["b_in"],
                      params["w_out"], params["b_out"], top_k=self.top_k,
                      capacity_factor=self.capacity_factor,
                      activation=self.activation.apply, token_mask=tmask)
        aux = (self.aux_weight * out.aux_loss
               + self.z_weight * out.router_z_loss)
        return out.y, {"moe_aux_loss": aux}
