"""Variational autoencoder layer + reconstruction distributions.

Analog of the reference's VAE stack (deeplearning4j-nn/.../nn/layers/
variational/VariationalAutoencoder.java:51 and nn/conf/layers/variational/
— GaussianReconstructionDistribution, BernoulliReconstructionDistribution,
ExponentialReconstructionDistribution, CompositeReconstructionDistribution,
ReconstructionDistribution SPI).

TPU-native redesign: the whole ELBO (encoder MLP → reparameterized sample →
decoder MLP → reconstruction log-likelihood + KL) is one pure function
differentiated by ``jax.grad`` — the reference hand-writes the full
backward pass through both towers. Used supervised, the layer outputs the
latent mean (same as the reference's activate()).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import FeedForwardType, InputType
from deeplearning4j_tpu.nn.layers.base import FeedForwardLayer, LayerContext
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.utils.serde import register_serializable

_HALF_LOG_2PI = 0.5 * jnp.log(2.0 * jnp.pi)


@dataclasses.dataclass(frozen=True)
class ReconstructionDistribution:
    """SPI: conf/layers/variational/ReconstructionDistribution.java."""

    def params_per_feature(self) -> int:
        raise NotImplementedError

    def log_prob(self, x: jnp.ndarray, dist_params: jnp.ndarray
                 ) -> jnp.ndarray:
        """Per-example log p(x|params). dist_params has
        n_in * params_per_feature features."""
        raise NotImplementedError

    def mean(self, dist_params: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError


@register_serializable
@dataclasses.dataclass(frozen=True)
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """N(mu, sigma^2) per feature; params = [mu | log(sigma^2)]
    (variational/GaussianReconstructionDistribution.java)."""
    activation: Activation = Activation.IDENTITY

    def params_per_feature(self) -> int:
        return 2

    def _split(self, dist_params):
        n = dist_params.shape[-1] // 2
        mu = self.activation.apply(dist_params[..., :n])
        log_var = dist_params[..., n:]
        return mu, log_var

    def log_prob(self, x, dist_params):
        mu, log_var = self._split(dist_params)
        inv_var = jnp.exp(-log_var)
        ll = -_HALF_LOG_2PI - 0.5 * log_var \
            - 0.5 * jnp.square(x - mu) * inv_var
        return jnp.sum(ll, axis=-1)

    def mean(self, dist_params):
        return self._split(dist_params)[0]


@register_serializable
@dataclasses.dataclass(frozen=True)
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Bernoulli(p) per feature, p through sigmoid by default
    (variational/BernoulliReconstructionDistribution.java)."""
    activation: Activation = Activation.SIGMOID

    def params_per_feature(self) -> int:
        return 1

    def log_prob(self, x, dist_params):
        p = jnp.clip(self.activation.apply(dist_params), 1e-7, 1 - 1e-7)
        ll = x * jnp.log(p) + (1.0 - x) * jnp.log1p(-p)
        return jnp.sum(ll, axis=-1)

    def mean(self, dist_params):
        return self.activation.apply(dist_params)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Exp(lambda) per feature; network emits gamma = log(lambda)
    (variational/ExponentialReconstructionDistribution.java)."""
    activation: Activation = Activation.IDENTITY

    def params_per_feature(self) -> int:
        return 1

    def log_prob(self, x, dist_params):
        gamma = self.activation.apply(dist_params)
        lam = jnp.exp(gamma)
        return jnp.sum(gamma - lam * x, axis=-1)

    def mean(self, dist_params):
        return jnp.exp(-self.activation.apply(dist_params))


@register_serializable
@dataclasses.dataclass(frozen=True)
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Different distributions over contiguous feature slices
    (variational/CompositeReconstructionDistribution.java).
    ``components`` = tuple of (n_features, distribution)."""
    components: Tuple = ()

    def params_per_feature(self) -> int:
        raise TypeError("composite: use total_params(n_in) slicing")

    def total_params(self) -> int:
        return sum(n * d.params_per_feature() for n, d in self.components)

    def total_features(self) -> int:
        return sum(n for n, _ in self.components)

    def log_prob(self, x, dist_params):
        ll = None
        xo = 0
        po = 0
        for n, dist in self.components:
            xs = x[..., xo:xo + n]
            ps = dist_params[..., po:po + n * dist.params_per_feature()]
            part = dist.log_prob(xs, ps)
            ll = part if ll is None else ll + part
            xo += n
            po += n * dist.params_per_feature()
        return ll

    def mean(self, dist_params):
        outs = []
        po = 0
        for n, dist in self.components:
            ps = dist_params[..., po:po + n * dist.params_per_feature()]
            outs.append(dist.mean(ps))
            po += n * dist.params_per_feature()
        return jnp.concatenate(outs, axis=-1)


@register_serializable
@dataclasses.dataclass(frozen=True)
class LossFunctionWrapper(ReconstructionDistribution):
    """Use a plain loss function as an (improper) reconstruction measure
    (variational/LossFunctionWrapper.java)."""
    loss: object = None
    activation: Activation = Activation.IDENTITY

    def params_per_feature(self) -> int:
        return 1

    def log_prob(self, x, dist_params):
        out = self.activation.apply(dist_params)
        if self.loss is None:
            per = jnp.sum(jnp.square(x - out), axis=-1)
        else:
            per = self.loss(x, out)  # LossFunction enum is callable
        return -per

    def mean(self, dist_params):
        return self.activation.apply(dist_params)


def _mlp_init(key, sizes, weight_init, dt):
    params = {}
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        kw, key = jax.random.split(key)
        params[f"W{i}"] = weight_init.init(kw, (a, b), a, b, dt)
        params[f"b{i}"] = jnp.zeros((b,), dt)
    return params


def _mlp_apply(params, x, activation, n_layers):
    for i in range(n_layers):
        x = activation.apply(
            jnp.einsum("...i,io->...o", x, params[f"W{i}"]) + params[f"b{i}"])
    return x


@register_serializable
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(FeedForwardLayer):
    """VAE as a layer (conf/layers/variational/VariationalAutoencoder.java;
    impl nn/layers/variational/VariationalAutoencoder.java:51).

    ``n_out`` is the latent size. Supervised forward outputs the latent
    mean; ``pretrain_loss`` is the negative ELBO used by
    MultiLayerNetwork.pretrain (the reference's pretrain path).
    """
    encoder_layer_sizes: Tuple[int, ...] = (256,)
    decoder_layer_sizes: Tuple[int, ...] = (256,)
    reconstruction_distribution: ReconstructionDistribution = \
        dataclasses.field(
            default_factory=GaussianReconstructionDistribution)
    pzx_activation: Activation = Activation.IDENTITY
    num_samples: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(self.n_out)

    @property
    def supports_pretrain(self) -> bool:
        return True

    def _dist_param_count(self, n_in: int) -> int:
        d = self.reconstruction_distribution
        if isinstance(d, CompositeReconstructionDistribution):
            return d.total_params()
        return n_in * d.params_per_feature()

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        dt = self.param_dtype()
        ke, km, kv, kd, ko = jax.random.split(key, 5)
        enc_sizes = (n_in,) + tuple(self.encoder_layer_sizes)
        dec_sizes = (self.n_out,) + tuple(self.decoder_layer_sizes)
        last_enc = enc_sizes[-1]
        last_dec = dec_sizes[-1]
        n_dist = self._dist_param_count(n_in)
        return {
            "enc": _mlp_init(ke, enc_sizes, self.weight_init, dt),
            "Wmu": self.weight_init.init(km, (last_enc, self.n_out),
                                         last_enc, self.n_out, dt),
            "bmu": jnp.zeros((self.n_out,), dt),
            "Wlv": self.weight_init.init(kv, (last_enc, self.n_out),
                                         last_enc, self.n_out, dt),
            "blv": jnp.zeros((self.n_out,), dt),
            "dec": _mlp_init(kd, dec_sizes, self.weight_init, dt),
            "Wout": self.weight_init.init(ko, (last_dec, n_dist),
                                          last_dec, n_dist, dt),
            "bout": jnp.zeros((n_dist,), dt),
        }

    # ---- supervised forward: latent mean ---------------------------------
    def apply(self, params, state, x, ctx: LayerContext):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        h = _mlp_apply(params["enc"], x, self.activation,
                       len(self.encoder_layer_sizes))
        mu = jnp.einsum("...i,io->...o", h, params["Wmu"]) + params["bmu"]
        return self.pzx_activation.apply(mu), state

    # ---- unsupervised: ELBO ----------------------------------------------
    def _encode(self, params, x):
        h = _mlp_apply(params["enc"], x, self.activation,
                       len(self.encoder_layer_sizes))
        mu = jnp.einsum("...i,io->...o", h, params["Wmu"]) + params["bmu"]
        log_var = jnp.einsum("...i,io->...o", h, params["Wlv"]) + params["blv"]
        return self.pzx_activation.apply(mu), log_var

    def _decode(self, params, z):
        d = _mlp_apply(params["dec"], z, self.activation,
                       len(self.decoder_layer_sizes))
        return jnp.einsum("...i,io->...o", d, params["Wout"]) + params["bout"]

    def pretrain_loss(self, params, x, key) -> jnp.ndarray:
        """Negative ELBO, averaged over the batch (and num_samples MC
        samples of z) — VariationalAutoencoder.computeGradientAndScore."""
        mu, log_var = self._encode(params, x)
        kl = -0.5 * jnp.sum(1.0 + log_var - jnp.square(mu)
                            - jnp.exp(log_var), axis=-1)
        total_ll = 0.0
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(key, s), mu.shape,
                                    mu.dtype)
            z = mu + jnp.exp(0.5 * log_var) * eps
            dist_params = self._decode(params, z)
            total_ll = total_ll + self.reconstruction_distribution.log_prob(
                x, dist_params)
        recon_ll = total_ll / self.num_samples
        return jnp.mean(kl - recon_ll)

    # ---- reference API extras -------------------------------------------
    def reconstruct(self, params, x, key=None):
        """x → encode(mean) → decode → distribution mean."""
        mu, _ = self._encode(params, x)
        return self.reconstruction_distribution.mean(self._decode(params, mu))

    def generate_at_mean_given_z(self, params, z):
        return self.reconstruction_distribution.mean(self._decode(params, z))

    def reconstruction_log_probability(self, params, x, key,
                                       num_samples: int = 5):
        """MC estimate of log p(x) (reconstructionLogProbability in the
        reference) via importance sampling at q(z|x)."""
        mu, log_var = self._encode(params, x)
        lls = []
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(key, s), mu.shape,
                                    mu.dtype)
            z = mu + jnp.exp(0.5 * log_var) * eps
            dist_params = self._decode(params, z)
            log_p_xz = self.reconstruction_distribution.log_prob(
                x, dist_params)
            log_p_z = jnp.sum(-_HALF_LOG_2PI - 0.5 * jnp.square(z), axis=-1)
            log_q = jnp.sum(-_HALF_LOG_2PI - 0.5 * log_var
                            - 0.5 * jnp.square(eps), axis=-1)
            lls.append(log_p_xz + log_p_z - log_q)
        stacked = jnp.stack(lls)
        return jax.scipy.special.logsumexp(stacked, axis=0) - jnp.log(
            float(num_samples))
