"""Attention and transformer layers.

The reference has no attention layers (SURVEY §2.11: model/tensor/
sequence parallelism and attention are ABSENT — "the TPU build must
design these fresh", §7.2 stage 7). These are the framework-native
building blocks for the BERT-class import target (BASELINE config 3) and
for the long-context path: the same multi-head attention math runs
single-chip here and sequence-parallel via parallel/ring_attention.py.

TPU-first choices:
- one packed QKV projection (a single MXU matmul) instead of three;
- softmax in float32 regardless of compute dtype (bf16-safe);
- masks are (N, T) sequence masks as everywhere else in the framework;
- no data-dependent shapes: padding stays in the sequence, masked out.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType, RecurrentType
from deeplearning4j_tpu.nn.layers.base import (
    FeedForwardLayer,
    Layer,
    LayerContext,
)
from deeplearning4j_tpu.nn.layers.normalization import LayerNormalization
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.utils.serde import register_serializable


def scaled_dot_product_attention(q, k, v, mask=None, causal=False):
    """Plain attention on (N, T, H, Dh) tensors; softmax in f32.

    ``mask``: (N, T_k) key validity mask. The single-chip reference path
    that parallel/ring_attention.py must match exactly.

    Internal score order is (N, Tq, Tk, H) — HEAD TRAILING — so both
    contractions keep (h, dh) as the packed-QKV tensor's trailing dims
    and XLA never relayouts the projection output (the (n,h,q,k) order
    cost ~0.23 ms of transpose copies per layer per direction at the
    BERT profile shape; measured 5.87 → 5.33 ms/layer fwd+bwd,
    bitwise-equal outputs)."""
    dh = q.shape[-1]
    # at least f32 for the softmax; f64 inputs stay f64 (gradient checks)
    sdt = jnp.promote_types(jnp.float32, q.dtype)
    s = jnp.einsum("nqhd,nkhd->nqkh", q, k).astype(sdt)
    s = s / jnp.sqrt(jnp.asarray(dh, sdt))
    # large-FINITE mask value: -inf rows make softmax's VJP emit NaN even
    # when the forward output is where-guarded (NaN * 0 cotangent), so a
    # fully-padded sequence would poison the whole batch's gradients
    neg = jnp.asarray(jnp.finfo(sdt).min / 2, sdt)
    valid = None
    if causal:
        tq, tk = s.shape[1], s.shape[2]
        qpos = jnp.arange(tq)[:, None, None]
        kpos = jnp.arange(tk)[None, :, None]
        s = jnp.where((kpos <= qpos)[None], s, neg)
    if mask is not None:
        valid = mask[:, None, :, None].astype(bool)
        s = jnp.where(valid, s, neg)
    p = jax.nn.softmax(s, axis=2)
    if valid is not None:
        # fully-masked rows: uniform softmax garbage → exact zeros
        p = jnp.where(valid.any(axis=2, keepdims=True), p, 0.0)
    return jnp.einsum("nqkh,nkhd->nqhd", p.astype(v.dtype), v)


@register_serializable
@dataclasses.dataclass(frozen=True)
class SelfAttentionLayer(FeedForwardLayer):
    """Multi-head self-attention over (N, T, F) with residual-free output
    projection: y = Attn(xWq, xWk, xWv)Wo. n_out = model width."""
    n_heads: int = 4
    causal: bool = False
    # queries/keys/values all from the input (self-attention)

    def __post_init__(self):
        if self.n_out and self.n_out % self.n_heads != 0:
            raise ValueError(
                f"n_out={self.n_out} not divisible by n_heads={self.n_heads}")

    def output_type(self, input_type: InputType) -> InputType:
        t = (input_type.timesteps
             if isinstance(input_type, RecurrentType) else None)
        return RecurrentType(self.n_out, t)

    def initialize(self, key, input_type):
        n_in = self.resolved_n_in(input_type)
        kq, ko = jax.random.split(key)
        dt = self.param_dtype()
        params = {
            # packed QKV: one matmul on the MXU. Column order is HEAD-major
            # ((head, which, dh)), so a contiguous column shard of Wqkv is a
            # set of whole heads — Megatron-style tensor parallelism
            # (parallel/tensor_parallel.py) then shards heads with plain
            # GSPMD dim tiling, no strided resharding.
            "Wqkv": self.weight_init.init(kq, (n_in, 3 * self.n_out),
                                          n_in, self.n_out, dt),
            "Wo": self.weight_init.init(ko, (self.n_out, self.n_out),
                                        self.n_out, self.n_out, dt),
        }
        if self.has_bias:
            params["bqkv"] = jnp.zeros((3 * self.n_out,), dt)
            params["bo"] = jnp.zeros((self.n_out,), dt)
        return params

    def _qkv(self, params, x):
        qkv = jnp.einsum("ntf,fe->nte", x, params["Wqkv"])
        if self.has_bias:
            qkv = qkv + params["bqkv"]
        n, t, _ = qkv.shape
        h, dh = self.n_heads, self.n_out // self.n_heads
        qkv = qkv.reshape(n, t, h, 3, dh)
        return qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]

    def apply(self, params, state, x, ctx: LayerContext):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        q, k, v = self._qkv(params, x)
        # helper-SPI dispatch: Pallas flash kernel on TPU, plain XLA
        # lowering elsewhere (ops/pallas_kernels.py)
        from deeplearning4j_tpu.ops.pallas_kernels import attention as _attn
        o = _attn(q, k, v, mask=ctx.mask, causal=self.causal)
        n, t = o.shape[0], o.shape[1]
        y = o.reshape(n, t, self.n_out)
        y = jnp.einsum("nte,eo->nto", y, params["Wo"])
        if self.has_bias:
            y = y + params["bo"]
        if ctx.mask is not None:
            y = y * ctx.mask[:, :, None].astype(y.dtype)
        return self.activation.apply(y), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class LearnedPositionalEmbedding(Layer):
    """Adds a learned position embedding to (N, T, F) inputs (BERT-style).
    ``max_len`` bounds the trainable table; sequences must be ≤ max_len."""
    max_len: int = 512
    weight_init: WeightInit = WeightInit.XAVIER

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def initialize(self, key, input_type):
        f = input_type.shape()[-1]
        dt = self.param_dtype()
        if self.weight_init == WeightInit.XAVIER:
            # BERT-style truncated-scale init for position tables
            return {"P": 0.02 * jax.random.normal(key, (self.max_len, f),
                                                  dt)}
        return {"P": self.weight_init.init(key, (self.max_len, f),
                                           self.max_len, f, dt)}

    def apply(self, params, state, x, ctx):
        t = x.shape[1]
        return x + params["P"][:t].astype(x.dtype), state


@register_serializable
@dataclasses.dataclass(frozen=True)
class TransformerEncoderBlock(FeedForwardLayer):
    """Pre-LN transformer block: x + MHA(LN(x)); x + FFN(LN(x)).
    The composition unit for BERT-class models. ``n_out`` is the model
    width (must equal the input width — residuals), ``ffn_mult`` the MLP
    expansion."""
    n_heads: int = 4
    ffn_mult: int = 4
    causal: bool = False
    ffn_activation: Activation = Activation.GELU
    attn_dropout: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        t = (input_type.timesteps
             if isinstance(input_type, RecurrentType) else None)
        return RecurrentType(self.n_out, t)

    def _parts(self):
        width = self.n_out
        attn = SelfAttentionLayer(
            n_in=width, n_out=width, n_heads=self.n_heads,
            causal=self.causal, weight_init=self.weight_init,
            dropout=self.attn_dropout, dtype=self.dtype,
            has_bias=self.has_bias)
        ln1 = LayerNormalization(dtype=self.dtype)
        ln2 = LayerNormalization(dtype=self.dtype)
        return attn, ln1, ln2

    def initialize(self, key, input_type):
        width = self.resolved_n_in(input_type)
        if self.n_out and width != self.n_out:
            raise ValueError(
                f"TransformerEncoderBlock needs n_in == n_out "
                f"(residuals); got {width} vs {self.n_out}")
        attn, ln1, ln2 = self._parts()
        ka, k1, k2, kf1, kf2 = jax.random.split(key, 5)
        rt = RecurrentType(width, None)
        dt = self.param_dtype()
        hidden = self.ffn_mult * width
        params = {
            "attn": attn.initialize(ka, rt),
            "ln1": ln1.initialize(k1, rt),
            "ln2": ln2.initialize(k2, rt),
            "W1": self.weight_init.init(kf1, (width, hidden), width,
                                        hidden, dt),
            "W2": self.weight_init.init(kf2, (hidden, width), hidden,
                                        width, dt),
        }
        if self.has_bias:
            params["b1"] = jnp.zeros((hidden,), dt)
            params["b2"] = jnp.zeros((width,), dt)
        return params

    def apply(self, params, state, x, ctx: LayerContext):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        attn, ln1, ln2 = self._parts()
        h, _ = ln1.apply(params["ln1"], {}, x, ctx)
        a, _ = attn.apply(params["attn"], {}, h, ctx)
        x = x + a
        h, _ = ln2.apply(params["ln2"], {}, x, ctx)
        f = jnp.einsum("ntf,fh->nth", h, params["W1"])
        if self.has_bias:
            f = f + params["b1"]
        f = self.ffn_activation.apply(f)
        f = jnp.einsum("nth,hf->ntf", f, params["W2"])
        if self.has_bias:
            f = f + params["b2"]
        y = x + f
        if ctx.mask is not None:
            y = y * ctx.mask[:, :, None].astype(y.dtype)
        return y, state
