"""YOLOv2 object-detection output layer + utilities.

Analog of the reference's objdetect package (deeplearning4j-nn/.../nn/
layers/objdetect/Yolo2OutputLayer.java:71, YoloUtils.java, conf in
nn/conf/layers/objdetect/Yolo2OutputLayer.java).

Layout (TPU-native NHWC): network output is (N, H, W, B*(5+C)) where B =
number of anchor boxes and C = classes; per box [tx, ty, tw, th, to,
class-logits...]. Labels are (N, H, W, 4+C): [cx, cy, w, h] in grid units
+ one-hot class; a cell with all-zero class vector holds no object (the
reference uses the same minibatch,4+C,H,W tensor transposed).

The whole loss — IoU-based responsibility assignment, coordinate SSE,
confidence and class terms — is pure jnp and differentiates via jax.grad;
the reference hand-writes ~400 lines of backward for this.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.inputs import ConvolutionalType, InputType
from deeplearning4j_tpu.nn.layers.base import Layer, LayerContext
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(Layer):
    """Loss-only layer (no params), like the reference's
    Yolo2OutputLayer. ``boxes`` = ((w, h), ...) anchor priors in grid
    units."""
    boxes: Tuple = ((1.0, 1.0),)
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    @property
    def has_params(self) -> bool:
        return False

    @property
    def num_boxes(self) -> int:
        return len(self.boxes)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _split(self, y):
        """(N,H,W,B*(5+C)) → tx,ty,tw,th,conf-logit,class-logits."""
        n, h, w, d = y.shape
        b = self.num_boxes
        y = y.reshape(n, h, w, b, d // b)
        return y[..., 0], y[..., 1], y[..., 2], y[..., 3], y[..., 4], \
            y[..., 5:]

    def _decode(self, y):
        """Activated predictions: center (grid units), size (grid units),
        confidence, class probabilities."""
        tx, ty, tw, th, to, tc = self._split(y)
        n, h, w = tx.shape[:3]
        gx = jnp.arange(w, dtype=y.dtype)[None, None, :, None]
        gy = jnp.arange(h, dtype=y.dtype)[None, :, None, None]
        anchors = jnp.asarray(self.boxes, y.dtype)  # (B, 2)
        cx = jax.nn.sigmoid(tx) + gx
        cy = jax.nn.sigmoid(ty) + gy
        bw = anchors[None, None, None, :, 0] * jnp.exp(tw)
        bh = anchors[None, None, None, :, 1] * jnp.exp(th)
        conf = jax.nn.sigmoid(to)
        probs = jax.nn.softmax(tc, axis=-1)
        return cx, cy, bw, bh, conf, probs

    def apply(self, params, state, x, ctx: LayerContext):
        return x, state  # raw activations pass through (like reference)

    # ---- loss ------------------------------------------------------------
    def compute_loss(self, params, state, x, labels, ctx: LayerContext):
        f32 = jnp.float32
        x = x.astype(f32)
        labels = jnp.asarray(labels, f32)
        tx, ty, tw, th, to, tc = self._split(x)
        n, h, w, b = tx.shape
        # ground truth
        g_cx, g_cy = labels[..., 0], labels[..., 1]          # (N,H,W)
        g_w = jnp.maximum(labels[..., 2], 1e-6)
        g_h = jnp.maximum(labels[..., 3], 1e-6)
        g_cls = labels[..., 4:]                              # (N,H,W,C)
        obj_mask = (jnp.sum(g_cls, axis=-1) > 0).astype(f32)  # (N,H,W)

        cx, cy, bw, bh, conf, _ = self._decode(x)
        # IoU of each predicted box vs the cell's ground-truth box
        gx1, gx2 = g_cx - g_w / 2, g_cx + g_w / 2
        gy1, gy2 = g_cy - g_h / 2, g_cy + g_h / 2
        px1, px2 = cx - bw / 2, cx + bw / 2
        py1, py2 = cy - bh / 2, cy + bh / 2
        ix = jnp.maximum(0.0, jnp.minimum(px2, gx2[..., None])
                         - jnp.maximum(px1, gx1[..., None]))
        iy = jnp.maximum(0.0, jnp.minimum(py2, gy2[..., None])
                         - jnp.maximum(py1, gy1[..., None]))
        inter = ix * iy
        union = bw * bh + (g_w * g_h)[..., None] - inter
        iou = inter / jnp.maximum(union, 1e-9)               # (N,H,W,B)

        # responsibility: best-IoU box per object cell (stop-grad, like
        # the reference's argmax assignment)
        best = jax.lax.stop_gradient(jnp.argmax(iou, axis=-1))  # (N,H,W)
        resp = jax.nn.one_hot(best, b, dtype=f32) * obj_mask[..., None]

        # coordinate loss on (sigma(t), sqrt size) vs truth
        cell_x = g_cx - jnp.floor(g_cx)
        cell_y = g_cy - jnp.floor(g_cy)
        anchors = jnp.asarray(self.boxes, f32)
        pred_sx = jax.nn.sigmoid(tx)
        pred_sy = jax.nn.sigmoid(ty)
        pred_sw = jnp.sqrt(jnp.maximum(
            anchors[None, None, None, :, 0] * jnp.exp(tw), 1e-9))
        pred_sh = jnp.sqrt(jnp.maximum(
            anchors[None, None, None, :, 1] * jnp.exp(th), 1e-9))
        loss_xy = jnp.square(pred_sx - cell_x[..., None]) + \
            jnp.square(pred_sy - cell_y[..., None])
        loss_wh = jnp.square(pred_sw - jnp.sqrt(g_w)[..., None]) + \
            jnp.square(pred_sh - jnp.sqrt(g_h)[..., None])
        coord = self.lambda_coord * jnp.sum(resp * (loss_xy + loss_wh))

        # confidence: responsible boxes → IoU target; others → 0
        iou_t = jax.lax.stop_gradient(iou)
        conf_obj = jnp.sum(resp * jnp.square(conf - iou_t))
        conf_noobj = self.lambda_no_obj * jnp.sum(
            (1.0 - resp) * jnp.square(conf))

        # classification: softmax xent through the responsible box's logits
        logp = jax.nn.log_softmax(tc, axis=-1)           # (N,H,W,B,C)
        resp_logp = jnp.sum(resp[..., None] * logp, axis=3)  # (N,H,W,C)
        cls = -jnp.sum(g_cls * resp_logp)

        total = coord + conf_obj + conf_noobj + cls
        return total / jnp.maximum(jnp.asarray(n, f32), 1.0)


@dataclasses.dataclass
class DetectedObject:
    """Analog of objdetect/DetectedObject.java."""
    example: int
    center_x: float
    center_y: float
    width: float
    height: float
    predicted_class: int
    confidence: float

    @property
    def top_left(self):
        return (self.center_x - self.width / 2,
                self.center_y - self.height / 2)

    @property
    def bottom_right(self):
        return (self.center_x + self.width / 2,
                self.center_y + self.height / 2)


def iou(a: DetectedObject, b: DetectedObject) -> float:
    """YoloUtils.iou."""
    ax1, ay1 = a.top_left
    ax2, ay2 = a.bottom_right
    bx1, by1 = b.top_left
    bx2, by2 = b.bottom_right
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = iw * ih
    union = a.width * a.height + b.width * b.height - inter
    return inter / union if union > 0 else 0.0


def get_predicted_objects(layer: Yolo2OutputLayer, network_output,
                          threshold: float = 0.5,
                          nms_threshold: Optional[float] = 0.4
                          ) -> List[DetectedObject]:
    """Decode + confidence-threshold + non-max suppression
    (YoloUtils.getPredictedObjects + nms). Decode runs on device; the
    small surviving set is filtered on host."""
    cx, cy, bw, bh, conf, probs = layer._decode(
        jnp.asarray(network_output, jnp.float32))
    cls = jnp.argmax(probs, axis=-1)
    score = conf * jnp.max(probs, axis=-1)
    cx, cy, bw, bh = (np.asarray(v) for v in (cx, cy, bw, bh))
    score = np.asarray(score)
    cls = np.asarray(cls)
    out: List[DetectedObject] = []
    idx = np.argwhere(score > threshold)
    for nidx, hy, wx, bi in idx:
        out.append(DetectedObject(
            int(nidx), float(cx[nidx, hy, wx, bi]),
            float(cy[nidx, hy, wx, bi]), float(bw[nidx, hy, wx, bi]),
            float(bh[nidx, hy, wx, bi]), int(cls[nidx, hy, wx, bi]),
            float(score[nidx, hy, wx, bi])))
    if nms_threshold is None:
        return out
    # greedy per-class NMS
    out.sort(key=lambda d: -d.confidence)
    kept: List[DetectedObject] = []
    for d in out:
        if all(not (k.example == d.example and
                    k.predicted_class == d.predicted_class and
                    iou(k, d) > nms_threshold) for k in kept):
            kept.append(d)
    return kept
