"""Output / loss layers and global pooling.

Analogs of the reference's ``OutputLayer``, ``RnnOutputLayer``, ``LossLayer``,
``CnnLossLayer``, ``GlobalPoolingLayer`` (deeplearning4j-nn/.../nn/conf/
layers/). An output layer is a dense projection plus a loss; models call
``compute_loss`` for training and ``apply`` for inference.

Numerics: when (SOFTMAX, MCXENT/NLL) or (SIGMOID, XENT) pair up, the loss is
computed on logits via fused log-sum-exp paths (ops/losses.py) — same math,
TPU-stable, and XLA folds it into the final matmul.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import (
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from deeplearning4j_tpu.nn.layers.base import Layer, LayerContext
from deeplearning4j_tpu.nn.layers.convolution import PoolingType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops import losses as L
from deeplearning4j_tpu.utils.serde import register_serializable


def _fused_loss(activation, loss_fn, labels, logits, mask):
    if activation is Activation.SOFTMAX and loss_fn in (
            L.LossFunction.MCXENT, L.LossFunction.NEGATIVELOGLIKELIHOOD):
        return L.stable_mcxent_from_logits(labels, logits, mask)
    if activation is Activation.SIGMOID and loss_fn is L.LossFunction.XENT:
        return L.stable_xent_from_logits(labels, logits, mask)
    return None


@register_serializable
@dataclasses.dataclass(frozen=True)
class OutputLayer(DenseLayer):
    """Dense + loss (reference: nn/conf/layers/OutputLayer; score math in
    BaseOutputLayer.computeScore)."""
    loss: L.LossFunction = L.LossFunction.MCXENT
    activation: Activation = Activation.SOFTMAX

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return RecurrentType(self.n_out, input_type.timesteps)
        return FeedForwardType(self.n_out)

    def pre_output(self, params, x):
        y = jnp.einsum("...i,io->...o", x, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return y

    def compute_loss(self, params, state, x, labels, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        logits = self.pre_output(params, x)
        fused = _fused_loss(self.activation, self.loss, labels, logits, ctx.mask)
        if fused is not None:
            return fused
        return self.loss(labels, self.activation.apply(logits), ctx.mask)


@register_serializable
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(OutputLayer):
    """Per-timestep output (reference: RnnOutputLayer). Input (N, T, F),
    labels (N, T, n_out), mask (N, T)."""

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(self.n_out, t)


@register_serializable
@dataclasses.dataclass(frozen=True)
class LossLayer(Layer):
    """Loss without params (reference: nn/conf/layers/LossLayer)."""
    loss: L.LossFunction = L.LossFunction.MCXENT
    activation: Activation = Activation.IDENTITY

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, state, x, ctx):
        return self.activation.apply(x), state

    def compute_loss(self, params, state, x, labels, ctx):
        fused = _fused_loss(self.activation, self.loss, labels, x, ctx.mask)
        if fused is not None:
            return fused
        return self.loss(labels, self.activation.apply(x), ctx.mask)


@register_serializable
@dataclasses.dataclass(frozen=True)
class RnnLossLayer(LossLayer):
    """Per-timestep loss WITHOUT a time-distributed dense projection
    (reference: nn/conf/layers/RnnLossLayer.java — unlike RnnOutputLayer
    there are no parameters; output activations size equals input size).
    Input/labels (N, T, F); the (N, T) sequence mask weights the
    per-timestep loss exactly as in RnnOutputLayer."""

    def output_type(self, input_type: InputType) -> InputType:
        if not isinstance(input_type, RecurrentType):
            raise ValueError(
                "RnnLossLayer expects recurrent (N, T, F) input, got "
                f"{input_type}")
        return input_type


@register_serializable
@dataclasses.dataclass(frozen=True)
class CnnLossLayer(LossLayer):
    """Per-pixel loss over NHWC maps (reference: CnnLossLayer). Labels have
    the same NHWC shape; mask broadcasting handles (N,H,W) masks."""

    def compute_loss(self, params, state, x, labels, ctx):
        n = x.shape[0]
        x2 = x.reshape(n, -1, x.shape[-1])
        l2 = labels.reshape(n, -1, labels.shape[-1])
        mask = ctx.mask
        if mask is not None:
            mask = mask.reshape(n, -1)
        ctx2 = dataclasses.replace(ctx, mask=mask)
        fused = _fused_loss(self.activation, self.loss, l2, x2, ctx2.mask)
        if fused is not None:
            return fused
        return self.loss(l2, self.activation.apply(x2), ctx2.mask)


@register_serializable
@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial or temporal dims (reference:
    nn/layers/pooling/GlobalPoolingLayer.java). CNN (N,H,W,C)→(N,C);
    RNN (N,T,F)→(N,F) honoring the sequence mask."""
    pooling_type: PoolingType = PoolingType.MAX
    pnorm: int = 2

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, ConvolutionalType):
            return FeedForwardType(input_type.channels)
        if isinstance(input_type, RecurrentType):
            return FeedForwardType(input_type.size)
        return input_type

    def apply(self, params, state, x, ctx):
        if x.ndim == 4:
            axes = (1, 2)
            mask = None
        else:
            axes = (1,)
            mask = ctx.mask
        if mask is not None:
            m = mask[:, :, None].astype(x.dtype)
            if self.pooling_type is PoolingType.MAX:
                x = jnp.where(m > 0, x, -jnp.inf)
                return jnp.max(x, axis=axes), state
            if self.pooling_type is PoolingType.SUM:
                return jnp.sum(x * m, axis=axes), state
            if self.pooling_type is PoolingType.AVG:
                denom = jnp.maximum(jnp.sum(m, axis=axes), 1.0)
                return jnp.sum(x * m, axis=axes) / denom, state
            if self.pooling_type is PoolingType.PNORM:
                pn = float(self.pnorm)
                return jnp.sum((jnp.abs(x) * m) ** pn, axis=axes) ** (1.0 / pn), state
        if self.pooling_type is PoolingType.MAX:
            return jnp.max(x, axis=axes), state
        if self.pooling_type is PoolingType.AVG:
            return jnp.mean(x, axis=axes), state
        if self.pooling_type is PoolingType.SUM:
            return jnp.sum(x, axis=axes), state
        if self.pooling_type is PoolingType.PNORM:
            pn = float(self.pnorm)
            return jnp.sum(jnp.abs(x) ** pn, axis=axes) ** (1.0 / pn), state
        raise ValueError(self.pooling_type)


@register_serializable
@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference: nn/conf/layers/CenterLossOutputLayer
    + nn/layers/training/CenterLossOutputLayer.java).

    Per-class feature centers live in the parameter tree and are learned by
    gradient descent on the ``lambda/2·||f − c_y||²`` term — functionally
    equivalent to the reference's EMA center update (its ``alpha``), which
    is SGD on the same objective with learning rate alpha.
    """
    alpha: float = 0.05   # kept for API parity; folds into center lr
    lambda_: float = 2e-4

    def initialize(self, key, input_type):
        params = super().initialize(key, input_type)
        n_in = self.resolved_n_in(input_type)
        params["centers"] = jnp.zeros((self.n_out, n_in),
                                      self.param_dtype())
        return params

    def compute_loss(self, params, state, x, labels, ctx):
        ctx, dk = ctx.split_rng()
        x = self.maybe_dropout(x, ctx, dk)
        logits = self.pre_output(params, x)
        fused = _fused_loss(self.activation, self.loss, labels, logits,
                            ctx.mask)
        base = fused if fused is not None else self.loss(
            labels, self.activation.apply(logits), ctx.mask)
        # center term: pull features toward their class center
        assigned = jnp.einsum("...c,ci->...i", labels,
                              params["centers"].astype(x.dtype))
        center = 0.5 * self.lambda_ * jnp.mean(
            jnp.sum(jnp.square(x - assigned), axis=-1))
        return base + center
