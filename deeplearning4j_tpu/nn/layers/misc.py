"""Wrapper + custom-function layers.

Analogs of the reference's FrozenLayer (nn/conf/layers/misc/FrozenLayer
.java — wraps any layer, blocks updates), and the SameDiff layer family
(nn/conf/layers/samediff/AbstractSameDiffLayer.java + nn/layers/samediff/
SameDiffLayer.java — user-defined graph inside a DL4J layer).

The SameDiff analog is the natural one for this framework: a SameDiff
graph is "a function you define symbolically"; in JAX that is just a
Python function of (params, x) — ``SameDiffLayer``/``LambdaLayer`` below
run arbitrary user jax code inside a model, fully jitted and
differentiated like everything else.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import FeedForwardType, InputType
from deeplearning4j_tpu.nn.layers.base import Layer, LayerContext
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class MaskLayer(Layer):
    """Applies the current mask array to the activations, passing them
    through otherwise (reference: nn/conf/layers/util/MaskLayer.java +
    nn/layers/util/MaskLayer.java — 2d, 3d time-series and 4d CNN
    activations). Zeroing the forward activations also zeroes the
    backward gradients at masked positions under ``jax.grad``, which is
    exactly the reference's backpropGradient contract."""

    @property
    def has_params(self):
        return False

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, state, x, ctx):
        m = ctx.mask
        if m is None:
            return x, state
        m = jnp.asarray(m, x.dtype)
        if x.ndim == 2:
            # per-example mask: (N,) or (N, 1) — reject (N, T) sequence
            # masks instead of silently using only timestep 0
            m2 = m.reshape(m.shape[0], -1)
            if m2.shape[1] != 1 or m.shape[0] != x.shape[0]:
                raise ValueError(
                    f"MaskLayer: 2d input {x.shape} needs a per-example "
                    f"(minibatch, 1) mask, got {m.shape}")
            m = m2
        elif x.ndim == 3:
            # (N, T) sequence mask over (N, T, F)
            if m.ndim != 2 or m.shape[0] != x.shape[0] \
                    or m.shape[1] != x.shape[1]:
                raise ValueError(
                    f"MaskLayer: 3d input {x.shape} needs a (minibatch, "
                    f"sequenceLength) mask, got {m.shape}")
            m = m[:, :, None]
        elif x.ndim == 4:
            # per-example mask over (N, H, W, C) maps
            m = m.reshape(m.shape[0], *([1] * (x.ndim - 1)))
        else:
            raise ValueError(f"MaskLayer: unsupported rank {x.ndim}")
        return x * m, state


@register_serializable
@dataclasses.dataclass(frozen=True)
class FrozenLayer(Layer):
    """Wrap any layer so its parameters never update
    (misc/FrozenLayer.java). Equivalent to ``underlying.frozen=True``;
    exists for API parity and for wrapping at runtime."""
    underlying: Optional[Layer] = None

    def __post_init__(self):
        object.__setattr__(self, "frozen", True)
        if self.underlying is not None and self.name is None:
            object.__setattr__(self, "name", self.underlying.name)

    @property
    def has_params(self) -> bool:
        return self.underlying.has_params

    def output_type(self, input_type: InputType) -> InputType:
        return self.underlying.output_type(input_type)

    def initialize(self, key, input_type):
        return self.underlying.initialize(key, input_type)

    def init_state(self, input_type):
        return self.underlying.init_state(input_type)

    def apply(self, params, state, x, ctx: LayerContext):
        # inference-mode ctx: frozen layers don't apply dropout (reference
        # FrozenLayer wraps with training=false semantics)
        frozen_ctx = dataclasses.replace(ctx, train=False)
        return self.underlying.apply(params, state, x, frozen_ctx)

    def compute_loss(self, params, state, x, labels, ctx):
        return self.underlying.compute_loss(params, state, x, labels, ctx)

    def __getattr__(self, item):
        # delegate conf attributes (n_out etc.) to the wrapped layer
        return getattr(object.__getattribute__(self, "underlying"), item)


@dataclasses.dataclass(frozen=True)
class LambdaLayer(Layer):
    """Parameter-free custom function layer (reference:
    nn/conf/layers/samediff/SameDiffLambdaLayer.java). ``fn(x) -> y`` must
    be pure jax. ``output_shape_fn`` maps input feature count to output
    feature count when it changes."""
    fn: Optional[Callable] = None
    output_type_fn: Optional[Callable] = None

    @property
    def has_params(self) -> bool:
        return False

    def output_type(self, input_type: InputType) -> InputType:
        if self.output_type_fn is not None:
            return self.output_type_fn(input_type)
        return input_type

    def apply(self, params, state, x, ctx: LayerContext):
        return self.fn(x), state


@dataclasses.dataclass(frozen=True)
class SameDiffLayer(Layer):
    """Custom layer with trainable params (reference:
    samediff/SameDiffLayer.java — defineLayer + defineParameters).

    - ``param_shapes``: dict name → shape (defineParameters)
    - ``fn(params, x) -> y`` pure jax (defineLayer)
    - ``out_type(input_type) -> InputType`` (getOutputType)
    - ``init_fn(key, name, shape) -> array`` optional custom init
      (initializeParameters); default: scaled normal
    """
    param_shapes: Optional[Dict[str, Tuple[int, ...]]] = None
    fn: Optional[Callable] = None
    out_type: Optional[Callable] = None
    init_fn: Optional[Callable] = None

    def output_type(self, input_type: InputType) -> InputType:
        if self.out_type is not None:
            return self.out_type(input_type)
        return input_type

    def initialize(self, key, input_type):
        params = {}
        for i, (name, shape) in enumerate(sorted(
                (self.param_shapes or {}).items())):
            k = jax.random.fold_in(key, i)
            if self.init_fn is not None:
                params[name] = self.init_fn(k, name, shape)
            else:
                fan_in = shape[0] if shape else 1
                params[name] = jax.random.normal(
                    k, shape, self.param_dtype()) / jnp.sqrt(
                        jnp.maximum(fan_in, 1.0))
        return params

    def apply(self, params, state, x, ctx: LayerContext):
        return self.fn(params, x), state
