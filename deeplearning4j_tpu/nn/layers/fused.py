"""Fused ResNet bottleneck block layer.

One layer = the whole bottleneck residual unit
(1×1 conv → BN → ReLU → 3×3 conv → BN → ReLU → 1×1 conv → BN →
(+shortcut) → ReLU), executed through the Pallas fused conv+BN kernels
(ops/fused_conv.py) so that BN batch statistics ride the conv output
pass and normalize+ReLU ride the consumer conv's input pass — no extra
HBM round trips per BatchNorm.

This is the block-granular analog of the reference's per-layer cuDNN
helper tier (CudnnConvolutionHelper.java:62, SURVEY §2.4): the zoo's
ResNet50 uses it when built with ``fused_blocks=True``; the math is
IDENTICAL to the unfused conv/BN/activation composition (equivalence
tested in tests/test_fused_conv.py / tests/test_fused_block.py).

Eval mode uses running stats — pure elementwise normalize that XLA
fuses fine — through the same fused kernels with the running-stat
scale/shift in the prologue.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import ConvolutionalType, InputType
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops.fused_conv import (
    fused_conv_bn_act,
    stats_to_scale_shift,
)
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class FusedBottleneckBlock(Layer):
    """ResNet-v1 bottleneck: f→f→4f channels, stride on the first 1×1
    (and the projection shortcut when ``downsample``)."""
    filters: int = 64
    stride: int = 1
    downsample: bool = False
    eps: float = 1e-5
    decay: float = 0.9
    # "pallas": the custom-kernel tier; "xla": plain-XLA convs with
    # Gram-matrix BN statistics for the expanding projections
    # (ops/fused_conv.py conv_bn_stats_xla) — no custom calls, no
    # layout copies, stats still never re-read the 4f activations
    impl: str = "pallas"

    def __post_init__(self):
        if self.impl not in ("pallas", "xla"):
            raise ValueError(
                f"FusedBottleneckBlock impl must be 'pallas' or 'xla', "
                f"got {self.impl!r}")

    # ---- shape ----------------------------------------------------------
    def _out_hw(self, it: ConvolutionalType) -> Tuple[int, int]:
        return (-(-it.height // self.stride), -(-it.width // self.stride))

    def output_type(self, input_type: InputType) -> InputType:
        it = input_type
        h, w = self._out_hw(it)
        return ConvolutionalType(h, w, self.filters * 4)

    # ---- params / state -------------------------------------------------
    def _bns(self):
        names = ["bn1", "bn2", "bn3"]
        if self.downsample:
            names.append("bnds")
        return names

    def initialize(self, key, input_type):
        cin = input_type.channels
        f, f4 = self.filters, self.filters * 4
        dt = self.param_dtype()
        ks = jax.random.split(key, 4)
        he = WeightInit.HE_NORMAL
        params = {
            "W1": he.init(ks[0], (cin, f), cin, f, dt),
            "W2": he.init(ks[1], (3, 3, f, f), 9 * f, 9 * f, dt),
            "W3": he.init(ks[2], (f, f4), f, f4, dt),
        }
        if self.downsample:
            params["Wds"] = he.init(ks[3], (cin, f4), cin, f4, dt)
        widths = {"bn1": f, "bn2": f, "bn3": f4, "bnds": f4}
        for bn in self._bns():
            params[f"{bn}_gamma"] = jnp.ones((widths[bn],), dt)
            params[f"{bn}_beta"] = jnp.zeros((widths[bn],), dt)
        return params

    def init_state(self, input_type):
        f, f4 = self.filters, self.filters * 4
        widths = {"bn1": f, "bn2": f, "bn3": f4, "bnds": f4}
        st = {}
        for bn in self._bns():
            st[f"{bn}_mean"] = jnp.zeros((widths[bn],), jnp.float32)
            st[f"{bn}_var"] = jnp.ones((widths[bn],), jnp.float32)
        return st

    # ---- forward --------------------------------------------------------
    def apply(self, params, state, x, ctx):
        f32 = jnp.float32
        train = ctx.train
        new_state = dict(state)

        def bn_form(name, stats, count):
            """(scale, shift) for the normalize folded into the NEXT
            kernel's prologue; updates running stats in train mode."""
            gamma = params[f"{name}_gamma"].astype(f32)
            beta = params[f"{name}_beta"].astype(f32)
            if train and stats is not None:
                inv, shift, mean, var = stats_to_scale_shift(
                    stats, count, gamma, beta, self.eps)
                new_state[f"{name}_mean"] = (
                    self.decay * state[f"{name}_mean"]
                    + (1 - self.decay) * mean).astype(f32)
                new_state[f"{name}_var"] = (
                    self.decay * state[f"{name}_var"]
                    + (1 - self.decay) * var).astype(f32)
                return inv, shift
            var = state[f"{name}_var"].astype(f32)
            mean = state[f"{name}_mean"].astype(f32)
            inv = gamma * jax.lax.rsqrt(var + self.eps)
            return inv, beta - mean * inv

        ones = jnp.ones((x.shape[-1],), f32)
        zeros = jnp.zeros((x.shape[-1],), f32)
        if self.impl == "xla":
            from deeplearning4j_tpu.ops.fused_conv import conv_bn_stats_xla
            conv = conv_bn_stats_xla
        else:
            conv = fused_conv_bn_act

        y1, st1 = conv(x, params["W1"], ones, zeros,
                       False, False, self.stride)
        m1 = y1.size // y1.shape[-1]
        s1, b1 = bn_form("bn1", st1, m1)

        y2, st2 = conv(y1, params["W2"], s1, b1, True, True, 1)
        m2 = y2.size // y2.shape[-1]
        s2, b2 = bn_form("bn2", st2, m2)

        y3, st3 = conv(y2, params["W3"], s2, b2, True, True, 1)
        m3 = y3.size // y3.shape[-1]
        s3, b3 = bn_form("bn3", st3, m3)

        # Tail normalize+add+ReLU. Pallas impl: on 2-D (M, C) views in
        # the compute dtype — 4-D/f32 tails made XLA pick the conv
        # activation layout and relayout-copy + upcast around every
        # Pallas kernel. XLA impl: stay 4-D — there the reshape itself
        # is the relayout.
        f4 = y3.shape[-1]
        out_shape = y3.shape
        flat = self.impl != "xla"
        v = (lambda a: a.reshape(-1, f4)) if flat else (lambda a: a)
        main = v(y3) * s3.astype(y3.dtype) + b3.astype(y3.dtype)
        if self.downsample:
            yds, stds = conv(x, params["Wds"], ones, zeros,
                             False, False, self.stride)
            sds, bds = bn_form("bnds", stds, yds.size // yds.shape[-1])
            shortcut = v(yds) * sds.astype(y3.dtype) \
                + bds.astype(y3.dtype)
        else:
            shortcut = v(x)
        out = jnp.maximum(main + shortcut, 0.0).astype(x.dtype)
        return out.reshape(out_shape), new_state
