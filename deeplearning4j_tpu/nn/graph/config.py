"""ComputationGraph configuration — arbitrary DAGs.

Analog of the reference's ``ComputationGraphConfiguration`` +
``GraphBuilder`` (deeplearning4j-nn/.../nn/conf/ComputationGraphConfiguration
.java; topological sort in nn/graph/ComputationGraph.java:1216 via Kahn's
algorithm). Multi-input/multi-output, layer nodes + combinator vertices.

    conf = (NeuralNetConfiguration.Builder().updater(Adam(1e-3))
            .graph_builder()
            .add_inputs("in")
            .add_layer("conv1", ConvolutionLayer(...), "in")
            .add_vertex("merge", MergeVertex(), "conv1", "conv2")
            .add_layer("out", OutputLayer(...), "merge")
            .set_outputs("out")
            .set_input_types(InputType.convolutional(32, 32, 3))
            .build())
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.config import GlobalConfig
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.graph.vertices import GraphVertex
from deeplearning4j_tpu.nn.preprocessors import infer_preprocessor, Preprocessor
from deeplearning4j_tpu.utils import serde
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class NodeDef:
    """One DAG node: exactly one of ``layer`` / ``vertex`` is set."""
    name: str
    inputs: Tuple[str, ...]
    layer: Optional[Layer] = None
    vertex: Optional[GraphVertex] = None
    preprocessor: Optional[Preprocessor] = None  # applied to single input


class GraphBuilder:
    def __init__(self, cfg: GlobalConfig):
        self._cfg = cfg
        self._inputs: List[str] = []
        self._input_types: List[InputType] = []
        self._nodes: List[NodeDef] = []
        self._outputs: List[str] = []

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str,
                  preprocessor: Optional[Preprocessor] = None) -> "GraphBuilder":
        if len(inputs) != 1:
            raise ValueError(
                f"layer node '{name}' needs exactly 1 input; wire multi-input"
                " through a MergeVertex/ElementWiseVertex first")
        layer = dataclasses.replace(layer, name=name)
        self._nodes.append(NodeDef(name, tuple(inputs), layer=layer,
                                   preprocessor=preprocessor))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex,
                   *inputs: str) -> "GraphBuilder":
        self._nodes.append(NodeDef(name, tuple(inputs), vertex=vertex))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop_type(self, kind: str) -> "GraphBuilder":
        """'standard' or 'tbptt' — same alias set as
        ListBuilder.backprop_type (reference: GraphBuilder.backpropType —
        ComputationGraph TBPTT fit, ComputationGraph.java:955)."""
        kind = kind.lower()
        if kind not in ("standard", "tbptt", "truncated_bptt"):
            raise ValueError(f"unknown backprop type {kind!r}")
        self._backprop_type = "tbptt" if kind != "standard" else "standard"
        return self

    def tbptt_fwd_length(self, k: int) -> "GraphBuilder":
        self._tbptt_fwd_length = int(k)
        return self

    def tbptt_back_length(self, k: int) -> "GraphBuilder":
        """Accepted for API parity; gradients truncate at chunk
        boundaries, so back length == fwd length here (same contract as
        ListBuilder.tbptt_back_length; checked at build())."""
        self._tbptt_back_length = int(k)
        return self

    def build(self) -> "ComputationGraphConfiguration":
        fwd = getattr(self, "_tbptt_fwd_length", 20)
        back = getattr(self, "_tbptt_back_length", fwd)
        if back != fwd:
            import warnings
            warnings.warn("tbptt_back_length != tbptt_fwd_length: "
                          "gradients truncate at the fwd chunk boundary "
                          f"({fwd}), not at {back}")
        conf = ComputationGraphConfiguration(
            global_config=self._cfg,
            network_inputs=tuple(self._inputs),
            network_input_types=tuple(self._input_types),
            nodes=tuple(self._nodes),
            network_outputs=tuple(self._outputs),
            backprop_type=getattr(self, "_backprop_type", "standard"),
            tbptt_fwd_length=fwd,
        )
        conf.resolve()
        return conf


@register_serializable
@dataclasses.dataclass
class ComputationGraphConfiguration:
    global_config: GlobalConfig
    network_inputs: Tuple[str, ...]
    network_input_types: Tuple[InputType, ...]
    nodes: Tuple[NodeDef, ...]
    network_outputs: Tuple[str, ...]
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20

    # ---- validation + shape inference -----------------------------------
    def resolve(self):
        by_name = {n.name: n for n in self.nodes}
        for inp in self.network_inputs:
            if inp in by_name:
                raise ValueError(f"node name collides with input: {inp}")
        for n in self.nodes:
            for src in n.inputs:
                if src not in by_name and src not in self.network_inputs:
                    raise ValueError(f"node '{n.name}' references unknown"
                                     f" input '{src}'")
        for out in self.network_outputs:
            if out not in by_name:
                raise ValueError(f"unknown output node: {out}")
        self._topo = self._topological_sort()
        if self.network_input_types:
            self._infer_types()
        return self

    def _topological_sort(self) -> List[str]:
        """Kahn's algorithm, same as the reference's topologicalSortOrder
        (ComputationGraph.java:1216)."""
        indeg: Dict[str, int] = {n.name: 0 for n in self.nodes}
        consumers: Dict[str, List[str]] = {}
        for n in self.nodes:
            for src in n.inputs:
                if src in indeg or src in self.network_inputs:
                    consumers.setdefault(src, []).append(n.name)
            indeg[n.name] = sum(1 for s in n.inputs
                                if s not in self.network_inputs)
        queue = [n.name for n in self.nodes if indeg[n.name] == 0]
        order: List[str] = []
        while queue:
            cur = queue.pop()
            order.append(cur)
            for c in consumers.get(cur, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.nodes):
            cyc = [k for k, v in indeg.items() if v > 0]
            raise ValueError(f"graph has a cycle involving: {cyc}")
        return order

    def _infer_types(self):
        if len(self.network_input_types) != len(self.network_inputs):
            raise ValueError("set_input_types arity != add_inputs arity")
        types: Dict[str, InputType] = dict(zip(self.network_inputs,
                                               self.network_input_types))
        new_nodes = {n.name: n for n in self.nodes}
        node_input_types: Dict[str, List[InputType]] = {}
        for name in self._topo:
            node = new_nodes[name]
            in_types = [types[s] for s in node.inputs]
            if node.layer is not None:
                it = in_types[0]
                pp = node.preprocessor or infer_preprocessor(it, node.layer)
                if pp is not None:
                    it = pp.output_type(it)
                layer = node.layer
                if hasattr(layer, "n_in") and layer.n_in is None and hasattr(
                        layer, "resolved_n_in"):
                    try:
                        layer = dataclasses.replace(
                            layer, n_in=layer.resolved_n_in(it))
                    except Exception:
                        pass
                node = dataclasses.replace(node, layer=layer, preprocessor=pp)
                new_nodes[name] = node
                types[name] = layer.output_type(it)
                node_in_types = [it]
            else:
                types[name] = node.vertex.output_type(*in_types)
                node_in_types = in_types
            node_input_types[name] = node_in_types
        self.nodes = tuple(new_nodes[n.name] for n in self.nodes)
        self._types = types
        self._node_input_types = node_input_types

    # ---- accessors ------------------------------------------------------
    def topological_order(self) -> List[str]:
        if not hasattr(self, "_topo"):
            self.resolve()
        return self._topo

    def node(self, name: str) -> NodeDef:
        return {n.name: n for n in self.nodes}[name]

    def activation_type(self, name: str) -> InputType:
        if not hasattr(self, "_types"):
            self.resolve()
        return self._types[name]

    def layer_input_type(self, name: str) -> InputType:
        if not hasattr(self, "_node_input_types"):
            self.resolve()
        return self._node_input_types[name][0]

    # ---- serde ----------------------------------------------------------
    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        conf = serde.from_json(s)
        conf.network_inputs = tuple(conf.network_inputs)
        conf.network_input_types = tuple(conf.network_input_types)
        conf.nodes = tuple(conf.nodes)
        conf.network_outputs = tuple(conf.network_outputs)
        conf.resolve()
        return conf
