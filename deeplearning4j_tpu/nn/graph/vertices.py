"""Graph vertices — DAG combinators for ComputationGraph.

Analogs of the reference's ``nn/conf/graph/`` vertex set (MergeVertex,
ElementWiseVertex, StackVertex/UnstackVertex, SubsetVertex, ScaleVertex,
ShiftVertex, L2NormalizeVertex, L2Vertex, ReshapeVertex, PreprocessorVertex,
and the rnn/ vertices LastTimeStepVertex, DuplicateToTimeSeriesVertex,
ReverseTimeSeriesVertex) and their runtime impls in ``nn/graph/vertex/impl/``.

A vertex is a pure stateless function over its input arrays — parameters
only exist on layer vertices (handled by the graph model, not here).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import (
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from deeplearning4j_tpu.nn.preprocessors import Preprocessor
from deeplearning4j_tpu.utils.serde import register_serializable


class GraphVertex:
    def output_type(self, *input_types: InputType) -> InputType:
        raise NotImplementedError

    def apply(self, *xs: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError


@register_serializable
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel (last) axis."""

    def output_type(self, *its):
        first = its[0]
        if isinstance(first, ConvolutionalType):
            return ConvolutionalType(first.height, first.width,
                                     sum(i.channels for i in its))
        if isinstance(first, RecurrentType):
            return RecurrentType(sum(i.size for i in its), first.timesteps)
        return FeedForwardType(sum(i.size for i in its))

    def apply(self, *xs):
        return jnp.concatenate(xs, axis=-1)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    op: str = "add"  # add|subtract|product|average|max

    def output_type(self, *its):
        return its[0]

    def apply(self, *xs):
        if self.op == "add":
            return sum(xs[1:], xs[0])
        if self.op == "subtract":
            return xs[0] - xs[1]
        if self.op == "product":
            y = xs[0]
            for x in xs[1:]:
                y = y * x
            return y
        if self.op == "average":
            return sum(xs[1:], xs[0]) / len(xs)
        if self.op == "max":
            y = xs[0]
            for x in xs[1:]:
                y = jnp.maximum(y, x)
            return y
        raise ValueError(self.op)


@register_serializable
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """Stack along the batch axis (reference: StackVertex)."""

    def output_type(self, *its):
        return its[0]

    def apply(self, *xs):
        return jnp.concatenate(xs, axis=0)


@register_serializable
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    from_index: int = 0
    stack_size: int = 1

    def output_type(self, *its):
        return its[0]

    def apply(self, x):
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step]


@register_serializable
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive, like the reference."""
    from_index: int = 0
    to_index: int = 0

    def output_type(self, *its):
        n = self.to_index - self.from_index + 1
        it = its[0]
        if isinstance(it, RecurrentType):
            return RecurrentType(n, it.timesteps)
        if isinstance(it, ConvolutionalType):
            return ConvolutionalType(it.height, it.width, n)
        return FeedForwardType(n)

    def apply(self, x):
        return x[..., self.from_index:self.to_index + 1]


@register_serializable
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def output_type(self, *its):
        return its[0]

    def apply(self, x):
        return x * self.scale


@register_serializable
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def output_type(self, *its):
        return its[0]

    def apply(self, x):
        return x + self.shift


@register_serializable
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def output_type(self, *its):
        return its[0]

    def apply(self, x):
        norm = jnp.linalg.norm(x.reshape(x.shape[0], -1), axis=1)
        norm = norm.reshape((-1,) + (1,) * (x.ndim - 1))
        return x / (norm + self.eps)


@register_serializable
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs → (N, 1)."""
    eps: float = 1e-8

    def output_type(self, *its):
        return FeedForwardType(1)

    def apply(self, a, b):
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertex):
    """Reshape trailing dims (batch dim preserved)."""
    shape: Tuple[int, ...] = ()

    def output_type(self, *its):
        s = self.shape
        if len(s) == 1:
            return FeedForwardType(s[0])
        if len(s) == 2:
            return RecurrentType(s[1], s[0])
        if len(s) == 3:
            return ConvolutionalType(s[0], s[1], s[2])
        raise ValueError(f"unsupported reshape arity: {s}")

    def apply(self, x):
        return x.reshape((x.shape[0],) + tuple(self.shape))


@register_serializable
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    preprocessor: Optional[Preprocessor] = None

    def output_type(self, *its):
        return self.preprocessor.output_type(its[0])

    def apply(self, x):
        return self.preprocessor.apply(x)


@register_serializable
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """(N, T, F) → (N, F) last *unmasked* timestep (reference:
    rnn/LastTimeStepVertex — mask-aware). The graph model passes the
    sequence mask when one is present."""

    def output_type(self, *its):
        return FeedForwardType(its[0].size)

    def apply(self, x, mask=None):
        if mask is None:
            return x[:, -1]
        idx = jnp.sum(mask.astype(jnp.int32), axis=1) - 1
        idx = jnp.clip(idx, 0, x.shape[1] - 1)
        return jnp.take_along_axis(
            x, idx[:, None, None].repeat(x.shape[-1], -1), axis=1)[:, 0]


@register_serializable
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """(N, F) → (N, T, F) broadcast over T taken from a reference input
    (reference: rnn/DuplicateToTimeSeriesVertex). Second input supplies T."""

    def output_type(self, *its):
        t = its[1].timesteps if len(its) > 1 and isinstance(
            its[1], RecurrentType) else None
        return RecurrentType(its[0].size, t)

    def apply(self, x, time_ref):
        t = time_ref.shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1]))


@register_serializable
@dataclasses.dataclass(frozen=True)
class ReverseTimeSeriesVertex(GraphVertex):
    def output_type(self, *its):
        return its[0]

    def apply(self, x):
        return jnp.flip(x, axis=1)
