"""Weight noise: parameter perturbation during the training forward pass.

Analog of deeplearning4j-nn/.../nn/conf/weightnoise/ (IWeightNoise.java,
WeightNoise.java, DropConnect.java). Applied to a layer's parameter tree
just before ``apply`` when training; the noise is NOT part of the stored
parameters, exactly like the reference (noise is regenerated per
iteration and gradients flow through the noisy values).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.distributions import Distribution, NormalDistribution
from deeplearning4j_tpu.nn.param_keys import is_bias_path as _is_bias
from deeplearning4j_tpu.utils.serde import register_serializable


@dataclasses.dataclass(frozen=True)
class IWeightNoise:
    """SPI: conf/weightnoise/IWeightNoise.java."""

    def apply_noise(self, params, key):
        raise NotImplementedError


@register_serializable
@dataclasses.dataclass(frozen=True)
class WeightNoise(IWeightNoise):
    """Additive or multiplicative noise drawn from a distribution
    (conf/weightnoise/WeightNoise.java)."""
    distribution: Distribution = dataclasses.field(
        default_factory=lambda: NormalDistribution(0.0, 0.01))
    additive: bool = True
    apply_to_bias: bool = False

    def apply_noise(self, params, key):
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]

        def noisy(i, path, p):
            if not self.apply_to_bias and _is_bias(path):
                return p
            k = jax.random.fold_in(key, i)
            noise = self.distribution.sample(k, p.shape, p.dtype)
            return p + noise if self.additive else p * noise

        flat = {path: noisy(i, path, leaf)
                for i, (path, leaf) in enumerate(leaves)}
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params),
            [flat[p] for p, _ in leaves])


@register_serializable
@dataclasses.dataclass(frozen=True)
class DropConnect(IWeightNoise):
    """Per-weight dropout (conf/weightnoise/DropConnect.java);
    ``p`` = drop probability, inverted scaling."""
    p: float = 0.5
    apply_to_bias: bool = False

    def apply_noise(self, params, key):
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        keep = 1.0 - self.p

        def drop(i, path, w):
            if not self.apply_to_bias and _is_bias(path):
                return w
            k = jax.random.fold_in(key, i)
            mask = jax.random.bernoulli(k, keep, w.shape)
            return jnp.where(mask, w / keep, 0.0).astype(w.dtype)

        flat = {path: drop(i, path, leaf)
                for i, (path, leaf) in enumerate(leaves)}
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params),
            [flat[p] for p, _ in leaves])
