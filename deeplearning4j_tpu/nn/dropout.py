"""Dropout family.

Analog of deeplearning4j-nn/.../nn/conf/dropout/ (IDropout.java,
Dropout.java, AlphaDropout.java, GaussianDropout.java, GaussianNoise.java).
All are pure functions of (x, key); layers call them on their INPUT during
training, matching the reference's input-dropout semantics.

NOTE on probability convention: the reference's ``Dropout(p)`` takes the
RETAIN probability; here ``p`` is the DROP probability (the modern
convention) — documented on each class.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_serializable


@dataclasses.dataclass(frozen=True)
class IDropout:
    """SPI: conf/dropout/IDropout.java."""

    def apply_dropout(self, x: jnp.ndarray, key) -> jnp.ndarray:
        raise NotImplementedError


@register_serializable
@dataclasses.dataclass(frozen=True)
class Dropout(IDropout):
    """Inverted dropout; ``p`` = drop probability."""
    p: float = 0.5

    def apply_dropout(self, x, key):
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class AlphaDropout(IDropout):
    """Self-normalizing dropout for SELU nets (conf/dropout/AlphaDropout
    .java): keeps mean/variance by dropping to alpha' and applying an
    affine correction."""
    p: float = 0.05

    def apply_dropout(self, x, key):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = 1.0 - self.p
        a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(key, keep, x.shape)
        return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class GaussianDropout(IDropout):
    """Multiplicative gaussian noise N(1, rate/(1-rate))
    (conf/dropout/GaussianDropout.java)."""
    rate: float = 0.5

    def apply_dropout(self, x, key):
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(key, x.shape, x.dtype)
        return x * noise


@register_serializable
@dataclasses.dataclass(frozen=True)
class GaussianNoise(IDropout):
    """Additive gaussian noise (conf/dropout/GaussianNoise.java)."""
    stddev: float = 0.1

    def apply_dropout(self, x, key):
        return x + self.stddev * jax.random.normal(key, x.shape, x.dtype)
