"""Input preprocessors — shape adapters between layer families.

Analog of the reference's ``nn/conf/preprocessor/`` package
(CnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor, etc.), with the
same auto-insertion behavior driven by ``InputType``
(deeplearning4j-nn/.../nn/conf/inputs/InputType.java). Pure reshapes —
XLA turns them into free layout changes.

Layouts: CNN is NHWC, RNN is (N, T, F) — see nn/inputs.py.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import (
    ConvolutionalFlatType,
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from deeplearning4j_tpu.utils.serde import register_serializable


class Preprocessor:
    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def apply(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError


@register_serializable
@dataclasses.dataclass(frozen=True)
class CnnToFeedForward(Preprocessor):
    height: int
    width: int
    channels: int

    def output_type(self, input_type):
        return FeedForwardType(self.height * self.width * self.channels)

    def apply(self, x):
        return x.reshape(x.shape[0], -1)


@register_serializable
@dataclasses.dataclass(frozen=True)
class FeedForwardToCnn(Preprocessor):
    height: int
    width: int
    channels: int

    def output_type(self, input_type):
        return ConvolutionalType(self.height, self.width, self.channels)

    def apply(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)


@register_serializable
@dataclasses.dataclass(frozen=True)
class FeedForwardToRnn(Preprocessor):
    """(N*T, F) → (N, T, F) is the reference's semantics; here the model
    keeps the batch dim, so this adapter broadcasts (N, F) → (N, 1, F)."""
    size: int

    def output_type(self, input_type):
        return RecurrentType(self.size, None)

    def apply(self, x):
        if x.ndim == 2:
            return x[:, None, :]
        return x


@register_serializable
@dataclasses.dataclass(frozen=True)
class RnnToFeedForward(Preprocessor):
    """(N, T, F) → applied per-timestep dense works natively on 3D, so this
    adapter is only needed when a strictly-2D layer follows; it flattens
    time into batch like the reference's RnnToFeedForwardPreProcessor."""
    size: int

    def output_type(self, input_type):
        return FeedForwardType(self.size)

    def apply(self, x):
        return x.reshape(-1, x.shape[-1])


@register_serializable
@dataclasses.dataclass(frozen=True)
class CnnToRnn(Preprocessor):
    """NHWC (N,H,W,C) → (N, H, W*C) treating height as time (reference:
    CnnToRnnPreProcessor flattens spatial dims per timestep)."""
    height: int
    width: int
    channels: int

    def output_type(self, input_type):
        return RecurrentType(self.width * self.channels, self.height)

    def apply(self, x):
        n, h, w, c = x.shape
        return x.reshape(n, h, w * c)


@register_serializable
@dataclasses.dataclass(frozen=True)
class RnnToCnn(Preprocessor):
    height: int
    width: int
    channels: int

    def output_type(self, input_type):
        return ConvolutionalType(self.height, self.width, self.channels)

    def apply(self, x):
        n = x.shape[0]
        return x.reshape(n, self.height, self.width, self.channels)


@register_serializable
@dataclasses.dataclass(frozen=True)
class UnflattenToCnn(Preprocessor):
    """ConvolutionalFlat input (N, H*W*C) → NHWC. The analog of the
    reference's FeedForwardToCnnPreProcessor inserted for
    ``InputType.convolutionalFlat`` (MNIST-style vectors)."""
    height: int
    width: int
    channels: int

    def output_type(self, input_type):
        return ConvolutionalType(self.height, self.width, self.channels)

    def apply(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)


def infer_preprocessor(prev: InputType, layer) -> Preprocessor | None:
    """Auto-insert an adapter when the previous output family doesn't match
    what the next layer expects — mirrors
    ``InputType.getPreProcessorForInputType`` dispatch in the reference."""
    from deeplearning4j_tpu.nn.layers.convolution import (
        Convolution1DLayer, ConvolutionLayer, SubsamplingLayer, Upsampling2D,
        ZeroPaddingLayer, Cropping2D, SpaceToDepthLayer, SpaceToBatchLayer,
    )
    from deeplearning4j_tpu.nn.layers.feedforward import (
        DenseLayer, EmbeddingLayer, EmbeddingSequenceLayer,
    )
    from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.layers.recurrent import (
        LSTM, SimpleRnn, Bidirectional, LastTimeStep,
    )

    conv_like = (ConvolutionLayer, SubsamplingLayer, Upsampling2D,
                 ZeroPaddingLayer, Cropping2D, SpaceToDepthLayer,
                 SpaceToBatchLayer)
    rnn_like = (LSTM, SimpleRnn, Bidirectional, LastTimeStep,
                Convolution1DLayer)

    if isinstance(prev, ConvolutionalFlatType) and isinstance(layer, conv_like):
        return UnflattenToCnn(prev.height, prev.width, prev.channels)
    if isinstance(prev, ConvolutionalType):
        if isinstance(layer, rnn_like):
            return CnnToRnn(prev.height, prev.width, prev.channels)
        if isinstance(layer, (DenseLayer, OutputLayer)) and not isinstance(
                layer, RnnOutputLayer):
            return CnnToFeedForward(prev.height, prev.width, prev.channels)
    if isinstance(prev, FeedForwardType) and isinstance(layer, rnn_like):
        return FeedForwardToRnn(prev.size)
    return None
