"""Input type system.

Analog of the reference's ``InputType`` (deeplearning4j-nn/.../nn/conf/inputs/
InputType.java), which drives shape inference and automatic insertion of
preprocessors between layer families (CNN→FF, FF→RNN, ...).

TPU-first difference: convolutional activations are **NHWC** (channels-last),
not the reference's NCHW. NHWC is the layout XLA's TPU convolution emitter
prefers (lane dimension = channels maps onto the 128-wide vector lanes), so
the framework is channels-last end to end and the Keras-import path needs no
transpose for TensorFlow-ordered weights.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deeplearning4j_tpu.utils.serde import register_serializable


class InputType:
    """Marker base. Shapes exclude the leading minibatch dimension."""

    def shape(self) -> Tuple[int, ...]:
        raise NotImplementedError

    @property
    def arity(self) -> int:
        return len(self.shape())

    @staticmethod
    def feed_forward(size: int) -> "FeedForwardType":
        return FeedForwardType(size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "RecurrentType":
        return RecurrentType(size, timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "ConvolutionalType":
        return ConvolutionalType(height, width, channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "ConvolutionalFlatType":
        return ConvolutionalFlatType(height, width, channels)


@register_serializable
@dataclasses.dataclass(frozen=True)
class FeedForwardType(InputType):
    size: int

    def shape(self):
        return (self.size,)


@register_serializable
@dataclasses.dataclass(frozen=True)
class RecurrentType(InputType):
    """(time, features) — time-major-within-example, batch-leading overall.

    The reference uses (batch, features, time); we use (batch, time, features)
    which is the natural layout for ``lax.scan`` over time and keeps the
    feature axis last (TPU lane dimension).
    """
    size: int
    timesteps: Optional[int] = None

    def shape(self):
        t = -1 if self.timesteps is None else self.timesteps
        return (t, self.size)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ConvolutionalType(InputType):
    """NHWC activation layout: shape() = (height, width, channels)."""
    height: int
    width: int
    channels: int

    def shape(self):
        return (self.height, self.width, self.channels)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ConvolutionalFlatType(InputType):
    """Flattened image input (e.g. MNIST 784-vectors) that a conv layer will
    reshape to NHWC. Mirrors the reference's ``InputType.convolutionalFlat``."""
    height: int
    width: int
    channels: int

    def shape(self):
        return (self.height * self.width * self.channels,)

    def unflatten(self) -> ConvolutionalType:
        return ConvolutionalType(self.height, self.width, self.channels)
