"""Pre-flight memory estimation.

Analog of the reference's ``nn/conf/memory/`` package
(``MemoryReport.java``, ``LayerMemoryReport.java``,
``NetworkMemoryReport.java`` — SURVEY §2.1 "Memory estimation"): a
per-layer + whole-network breakdown of parameter, gradient, updater-state
and activation memory for a given minibatch size, produced *before*
training so HBM fits can be checked up front.

TPU-native twist: beyond the analytic estimate the real, authoritative
number comes from XLA itself — :func:`xla_memory_analysis` compiles the
model's forward (or training) step and returns the compiled executable's
buffer-assignment statistics (``compiled.memory_analysis()``), which is
what actually determines whether the program fits in HBM. The reference
has no equivalent (its workspaces are dynamic); this is the
"workspaces become compiled-graph memory planning" translation (SURVEY
§2.14, §7.1).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

# Per-parameter updater-state slots (Adam keeps m and v → 2, momentum → 1).
_UPDATER_STATE_SLOTS = {
    "Sgd": 0, "NoOp": 0,
    "Nesterovs": 1, "AdaGrad": 1, "RmsProp": 1,
    "Adam": 2, "AdamW": 2, "AdaMax": 2, "Nadam": 2, "AdaDelta": 2,
    "AMSGrad": 3,
}


def _nelems(shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= max(int(d), 1)  # unknown time dim (-1) counted as 1 per step
    return n


@dataclass
class LayerMemoryReport:
    """Per-layer estimate (reference: LayerMemoryReport.Builder)."""

    layer_name: str
    layer_type: str
    parameter_count: int
    activation_elements_per_example: int
    updater_state_slots: int

    def total_bytes(self, batch_size: int, dtype_bytes: int = 4,
                    training: bool = True) -> int:
        fixed = self.parameter_count * dtype_bytes
        if training:
            # gradients mirror params; updater state per slot
            fixed += self.parameter_count * dtype_bytes
            fixed += (self.parameter_count * self.updater_state_slots
                      * dtype_bytes)
        var = self.activation_elements_per_example * batch_size * dtype_bytes
        if training:
            var *= 2  # activation gradients in backward
        return fixed + var


@dataclass
class NetworkMemoryReport:
    """Whole-network roll-up (reference: NetworkMemoryReport)."""

    layer_reports: List[LayerMemoryReport] = field(default_factory=list)
    model_name: str = "MultiLayerNetwork"

    @property
    def total_parameters(self) -> int:
        return sum(r.parameter_count for r in self.layer_reports)

    def total_bytes(self, batch_size: int, dtype_bytes: int = 4,
                    training: bool = True) -> int:
        return sum(r.total_bytes(batch_size, dtype_bytes, training)
                   for r in self.layer_reports)

    def to_json(self) -> str:
        return json.dumps({
            "model": self.model_name,
            "total_parameters": self.total_parameters,
            "layers": [{
                "name": r.layer_name, "type": r.layer_type,
                "parameters": r.parameter_count,
                "activation_elements_per_example":
                    r.activation_elements_per_example,
                "updater_state_slots": r.updater_state_slots,
            } for r in self.layer_reports],
        }, indent=2)

    def __str__(self) -> str:
        lines = [f"NetworkMemoryReport: {self.model_name} "
                 f"({self.total_parameters:,} params)"]
        lines.append(f"  {'layer':<24}{'type':<26}{'params':>12}"
                     f"{'act/ex':>12}")
        for r in self.layer_reports:
            lines.append(f"  {r.layer_name:<24}{r.layer_type:<26}"
                         f"{r.parameter_count:>12,}"
                         f"{r.activation_elements_per_example:>12,}")
        for bs in (1, 32):
            mb = self.total_bytes(bs) / (1 << 20)
            lines.append(f"  train memory @ batch {bs}: {mb:,.1f} MB (fp32)")
        return "\n".join(lines)


def memory_report(conf, model_name: Optional[str] = None
                  ) -> NetworkMemoryReport:
    """Build a NetworkMemoryReport from a MultiLayerConfiguration.

    Uses ``jax.eval_shape`` over each layer's ``initialize`` so parameter
    counts come from the real init code without allocating anything.
    """
    input_types, _pre = conf.resolve_shapes()
    key = jax.random.PRNGKey(0)
    reports: List[LayerMemoryReport] = []
    for i, layer in enumerate(conf.layers):
        it = input_types[i]
        try:
            shapes = jax.eval_shape(lambda l=layer, t=it: l.initialize(key, t))
            pcount = sum(int(np.prod(s.shape))
                         for s in jax.tree_util.tree_leaves(shapes))
        except Exception:
            pcount = 0
        out_t = layer.output_type(it)
        name = getattr(layer, "name", None) or f"layer{i}"
        upd = getattr(layer, "updater", None) or getattr(
            conf.global_config, "updater", None)
        slots = _UPDATER_STATE_SLOTS.get(type(upd).__name__, 2) if upd else 2
        reports.append(LayerMemoryReport(
            layer_name=name, layer_type=type(layer).__name__,
            parameter_count=pcount,
            activation_elements_per_example=_nelems(out_t.shape()),
            updater_state_slots=slots))
    return NetworkMemoryReport(reports, model_name or "MultiLayerNetwork")


def xla_memory_analysis(model, batch_size: int = 1,
                        train: bool = False) -> Dict[str, int]:
    """Authoritative memory numbers from the compiled XLA executable.

    Compiles the model's forward (or full training step when
    ``train=True``) with AOT lowering and returns the buffer-assignment
    stats XLA reports: argument/output/temp/generated-code sizes in bytes.
    This is the TPU answer to "will it fit in HBM".
    """
    import jax.numpy as jnp

    conf = model.conf
    conf.resolve_shapes()
    in_shape = (batch_size,) + tuple(
        d if d > 0 else 8 for d in conf.input_type.shape())
    x = jnp.zeros(in_shape, jnp.float32)
    params = model.train_state.params
    mstate = model.train_state.model_state

    if train:
        # Lower the FULL train step (loss + backward + optimizer update) so
        # gradients and updater state count toward the number reported —
        # the forward alone badly underestimates training HBM.
        step = model._build_train_step()
        out_t = model.layers[-1].output_type(model._input_types[-1])
        y_shape = (batch_size,) + tuple(
            d if d > 0 else 8 for d in out_t.shape())
        y = jnp.zeros(y_shape, jnp.float32)
        lowered = step.lower(model.train_state, x, y, None, None,
                             jax.random.PRNGKey(0))
    else:
        def fwd(params, mstate, x):
            out, _ = model._forward(params, mstate, x, None, False, None)
            return out

        lowered = jax.jit(fwd).lower(params, mstate, x)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    if ma is None:  # backend without memory analysis
        return {}
    return {
        "argument_size_in_bytes": int(ma.argument_size_in_bytes),
        "output_size_in_bytes": int(ma.output_size_in_bytes),
        "temp_size_in_bytes": int(ma.temp_size_in_bytes),
        "generated_code_size_in_bytes":
            int(ma.generated_code_size_in_bytes),
        "total_bytes": int(ma.argument_size_in_bytes
                           + ma.output_size_in_bytes
                           + ma.temp_size_in_bytes),
    }
