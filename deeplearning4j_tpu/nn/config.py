"""Network configuration: builders + JSON serde.

Analog of the reference's config system (deeplearning4j-nn/.../nn/conf/
NeuralNetConfiguration.java:82, Builder at :584; MultiLayerConfiguration
.java:55; ComputationGraphConfiguration.java), with the same builder-pattern
API a DL4J user expects:

    conf = (NeuralNetConfiguration.Builder()
            .seed(123)
            .updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), ...))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())

Shape inference runs at build time: each layer's ``InputType`` is computed
and preprocessors are auto-inserted (nn/preprocessors.py), like the
reference's ``MultiLayerConfiguration.Builder.build`` does via
``InputType.getPreProcessorForInputType``.

Configs serialize to JSON (``to_json``/``from_json``) through the explicit
type registry in utils/serde.py — the analog of the reference's Jackson +
classpath-scanning subtype discovery (NeuralNetConfiguration.java:434).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.preprocessors import Preprocessor, infer_preprocessor
from deeplearning4j_tpu.optimize.updaters import (
    GradientNormalizationConfig,
    Sgd,
    Updater,
)
from deeplearning4j_tpu.utils import serde
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclasses.dataclass(frozen=True)
class GlobalConfig:
    """Cross-layer hyperparameters set on NeuralNetConfiguration.Builder."""
    seed: int = 12345
    updater: Updater = dataclasses.field(default_factory=lambda: Sgd(1e-3))
    gradient_normalization: GradientNormalizationConfig = dataclasses.field(
        default_factory=GradientNormalizationConfig)
    l1: float = 0.0
    l2: float = 0.0
    dtype: str = "float32"          # param dtype
    compute_dtype: str = "float32"  # activation dtype ("bfloat16" for MXU speed)
    mini_batch: bool = True


class NeuralNetConfiguration:
    """Entry point; only hosts the Builder, matching reference ergonomics."""

    class Builder:
        def __init__(self):
            self._cfg = GlobalConfig()

        def _replace(self, **kw):
            self._cfg = dataclasses.replace(self._cfg, **kw)
            return self

        def seed(self, s: int):
            return self._replace(seed=int(s))

        def updater(self, u: Updater):
            return self._replace(updater=u)

        def l1(self, v: float):
            return self._replace(l1=v)

        def l2(self, v: float):
            return self._replace(l2=v)

        def gradient_normalization(self, kind: str, threshold: float = 1.0):
            return self._replace(gradient_normalization=
                                 GradientNormalizationConfig(kind, threshold))

        def dtype(self, dt: str):
            return self._replace(dtype=dt)

        def compute_dtype(self, dt: str):
            return self._replace(compute_dtype=dt)

        def list(self) -> "ListBuilder":
            return ListBuilder(self._cfg)

        def graph_builder(self) -> "GraphBuilder":
            from deeplearning4j_tpu.nn.graph.config import GraphBuilder
            return GraphBuilder(self._cfg)


class ListBuilder:
    """Sequential-model builder (reference: NeuralNetConfiguration.Builder
    .list() → MultiLayerConfiguration.Builder)."""

    def __init__(self, cfg: GlobalConfig):
        self._cfg = cfg
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._preprocessors: Dict[int, Preprocessor] = {}
        self._backprop_type: str = "standard"
        self._tbptt_fwd_length: int = 20
        self._tbptt_back_length: int = 20

    def backprop_type(self, kind: str) -> "ListBuilder":
        """'standard' or 'tbptt' (reference: BackpropType.TruncatedBPTT,
        MultiLayerConfiguration builder — SURVEY §5.7)."""
        kind = kind.lower()
        if kind not in ("standard", "tbptt", "truncated_bptt"):
            raise ValueError(f"unknown backprop type {kind!r}")
        self._backprop_type = "tbptt" if kind != "standard" else "standard"
        return self

    def tbptt_fwd_length(self, k: int) -> "ListBuilder":
        self._tbptt_fwd_length = int(k)
        return self

    def tbptt_back_length(self, k: int) -> "ListBuilder":
        """Stored for config parity; truncation happens at the chunk
        boundary, so the effective backward length always equals
        tbptt_fwd_length (a warning is emitted when they differ)."""
        self._tbptt_back_length = int(k)
        if self._tbptt_back_length != self._tbptt_fwd_length:
            import warnings
            warnings.warn(
                "tbptt_back_length != tbptt_fwd_length: gradients truncate "
                "at the fwd-length chunk boundary; back length is ignored",
                stacklevel=2)
        return self

    def layer(self, layer: Layer) -> "ListBuilder":
        self._layers.append(layer)
        return self

    def set_input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def input_pre_processor(self, idx: int, pp: Preprocessor) -> "ListBuilder":
        self._preprocessors[idx] = pp
        return self

    def build(self) -> "MultiLayerConfiguration":
        if not self._layers:
            raise ValueError("no layers configured")
        layers = []
        for i, l in enumerate(self._layers):
            updates = {}
            if l.name is None:
                updates["name"] = f"layer_{i}"
            if l.l1 == 0.0 and self._cfg.l1:
                updates["l1"] = self._cfg.l1
            if l.l2 == 0.0 and self._cfg.l2:
                updates["l2"] = self._cfg.l2
            layers.append(dataclasses.replace(l, **updates) if updates else l)
        conf = MultiLayerConfiguration(
            global_config=self._cfg,
            layers=tuple(layers),
            input_type=self._input_type,
            manual_preprocessors=dict(self._preprocessors),
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd_length,
            tbptt_back_length=self._tbptt_back_length,
        )
        conf.resolve_shapes()  # validate at build time, like the reference
        return conf


@register_serializable
@dataclasses.dataclass
class MultiLayerConfiguration:
    """Sequential stack config (reference: MultiLayerConfiguration.java:55)."""
    global_config: GlobalConfig
    layers: Tuple[Layer, ...]
    input_type: Optional[InputType] = None
    manual_preprocessors: Dict[int, Preprocessor] = dataclasses.field(
        default_factory=dict)
    # Truncated BPTT (reference: BackpropType.TruncatedBPTT +
    # tbpttFwdLength/tbpttBackLength — SURVEY §5.7). On TPU the truncation
    # boundary is the jitted-step boundary: each tbptt_fwd_length chunk is
    # one optimizer step and RNN carries cross chunks via stop_gradient.
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def resolve_shapes(self):
        """Compute per-layer input types + auto preprocessors.

        Returns (input_types, preprocessors) where input_types[i] is what
        layer i receives (post-preprocessor).
        """
        if self.input_type is None:
            raise ValueError(
                "set_input_type(...) is required for shape inference")
        input_types: List[InputType] = []
        preprocessors: Dict[int, Preprocessor] = {}
        cur = self.input_type
        resolved_layers = list(self.layers)
        for i, layer in enumerate(resolved_layers):
            pp = self.manual_preprocessors.get(i)
            if pp is None:
                pp = infer_preprocessor(cur, layer)
            if pp is not None:
                preprocessors[i] = pp
                cur = pp.output_type(cur)
            # infer n_in where the layer supports it (reference: setNIn)
            if hasattr(layer, "n_in") and layer.n_in is None and hasattr(
                    layer, "resolved_n_in"):
                try:
                    n_in = layer.resolved_n_in(cur)
                    layer = dataclasses.replace(layer, n_in=n_in)
                    resolved_layers[i] = layer
                except Exception:
                    pass
            input_types.append(cur)
            cur = layer.output_type(cur)
        self.layers = tuple(resolved_layers)
        self._input_types = input_types
        self._auto_preprocessors = preprocessors
        self._output_type = cur
        return input_types, preprocessors

    @property
    def output_type(self) -> InputType:
        if not hasattr(self, "_output_type"):
            self.resolve_shapes()
        return self._output_type

    def layer_input_types(self) -> List[InputType]:
        if not hasattr(self, "_input_types"):
            self.resolve_shapes()
        return self._input_types

    def preprocessors(self) -> Dict[int, Preprocessor]:
        if not hasattr(self, "_auto_preprocessors"):
            self.resolve_shapes()
        return self._auto_preprocessors

    # ---- serde ----------------------------------------------------------
    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        conf = serde.from_json(s)
        if not isinstance(conf, MultiLayerConfiguration):
            raise TypeError("JSON did not decode to MultiLayerConfiguration")
        # dict keys arrive as strings from JSON
        conf.manual_preprocessors = {int(k): v for k, v in
                                     conf.manual_preprocessors.items()}
        conf.layers = tuple(conf.layers)
        conf.resolve_shapes()
        return conf


# Re-export for __init__ convenience; the DAG config lives in nn/graph/.
def __getattr__(name):
    if name == "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.graph.config import (
            ComputationGraphConfiguration as CGC)
        return CGC
    raise AttributeError(name)
