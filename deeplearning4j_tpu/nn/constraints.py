"""Parameter constraints, applied after each optimizer update.

Analog of deeplearning4j-nn/.../nn/conf/constraint/ (MaxNormConstraint
.java, MinMaxNormConstraint.java, UnitNormConstraint.java, NonNegative
Constraint.java). The projection runs INSIDE the jitted train step (see
optimize/solver.make_train_step's ``constrain_fn``), so it fuses with the
update — no extra device round trip.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.param_keys import is_bias_path, is_weight_path
from deeplearning4j_tpu.utils.serde import register_serializable


def _weight_axes(w: jnp.ndarray) -> Tuple[int, ...]:
    """Norm is taken over all axes except the last (output dim) —
    matching the reference's default dimensions for dense/conv weights."""
    return tuple(range(max(w.ndim - 1, 1)))


@dataclasses.dataclass(frozen=True)
class LayerConstraint:
    """SPI: conf/constraint/ BaseConstraint. ``apply_to_bias`` default off,
    like the reference (constraints apply to weights only by default)."""
    apply_to_bias: bool = False

    def project(self, w: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def apply(self, params):
        def go(path, p):
            if not self.apply_to_bias and is_bias_path(path):
                return p
            if not is_weight_path(path) and not is_bias_path(path):
                return p  # statistics-like params (class centers): never
            return self.project(p)

        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params),
            [go(path, leaf) for path, leaf in leaves])


@register_serializable
@dataclasses.dataclass(frozen=True)
class MaxNormConstraint(LayerConstraint):
    max_norm: float = 2.0

    def project(self, w):
        norm = jnp.sqrt(jnp.sum(jnp.square(w), axis=_weight_axes(w),
                                keepdims=True) + 1e-12)
        return w * jnp.minimum(1.0, self.max_norm / norm)


@register_serializable
@dataclasses.dataclass(frozen=True)
class MinMaxNormConstraint(LayerConstraint):
    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0  # interpolation rate toward the clipped norm

    def project(self, w):
        norm = jnp.sqrt(jnp.sum(jnp.square(w), axis=_weight_axes(w),
                                keepdims=True) + 1e-12)
        clipped = jnp.clip(norm, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1.0 - self.rate) * norm
        return w * (target / norm)


@register_serializable
@dataclasses.dataclass(frozen=True)
class UnitNormConstraint(LayerConstraint):
    def project(self, w):
        norm = jnp.sqrt(jnp.sum(jnp.square(w), axis=_weight_axes(w),
                                keepdims=True) + 1e-12)
        return w / norm


@register_serializable
@dataclasses.dataclass(frozen=True)
class NonNegativeConstraint(LayerConstraint):
    def project(self, w):
        return jnp.maximum(w, 0.0)
