"""Weight distributions.

Analog of deeplearning4j-nn/.../nn/conf/distribution/ (NormalDistribution
.java, UniformDistribution.java, TruncatedNormalDistribution.java,
LogNormalDistribution.java, BinomialDistribution.java, ConstantDistribution
.java, OrthogonalDistribution.java). Each is both a sampler (weight noise)
and a weight initializer: ``init(key, shape, fan_in, fan_out, dtype)``
matches ops/initializers.WeightInit.init so a Distribution can be passed
anywhere a WeightInit is accepted (the reference's
``WeightInit.DISTRIBUTION`` + ``dist(...)`` builder pattern).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_serializable


@dataclasses.dataclass(frozen=True)
class Distribution:
    def sample(self, key, shape, dtype=jnp.float32) -> jnp.ndarray:
        raise NotImplementedError

    # WeightInit-compatible signature
    def init(self, key, shape, fan_in: int, fan_out: int,
             dtype=jnp.float32, gain: float = 1.0) -> jnp.ndarray:
        return gain * self.sample(key, tuple(shape), dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class NormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(key, shape, dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class UniformDistribution(Distribution):
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, self.lower, self.upper)


@register_serializable
@dataclasses.dataclass(frozen=True)
class TruncatedNormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class LogNormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        return jnp.exp(self.mean + self.std *
                       jax.random.normal(key, shape, dtype))


@register_serializable
@dataclasses.dataclass(frozen=True)
class BinomialDistribution(Distribution):
    trials: int = 1
    probability: float = 0.5

    def sample(self, key, shape, dtype=jnp.float32):
        draws = jax.random.bernoulli(
            key, self.probability, (self.trials,) + tuple(shape))
        return jnp.sum(draws, axis=0).astype(dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ConstantDistribution(Distribution):
    value: float = 0.0

    def sample(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


@register_serializable
@dataclasses.dataclass(frozen=True)
class OrthogonalDistribution(Distribution):
    gain: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        if len(shape) < 2:
            raise ValueError("orthogonal init needs >= 2 dims")
        rows = shape[0]
        cols = int(jnp.prod(jnp.asarray(shape[1:])))
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)),
                              jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)
