"""Shared parameter-key classification.

The framework's parameter trees use short conventional leaf names; several
subsystems (L1/L2 regularization in nn/layers/base.py, weight noise,
constraints) must treat bias-like parameters differently from weights —
this is the single source of truth for that classification (the
reference's analog: ParamInitializer.isBiasParam / isWeightParam,
nn/api/ParamInitializer.java).
"""

BIAS_KEYS = ("b", "vb", "beta", "mean", "var", "pI", "pF", "pO",
             "bmu", "blv", "bout")

# Neither weight nor bias: statistics-like parameters that must never be
# regularized or constrained (CenterLossOutputLayer's per-class centers —
# the reference updates them by EMA, never through weight decay).
EXCLUDED_KEYS = ("centers",)


def _key(path) -> str:
    return getattr(path[-1], "key", None)


def is_bias_path(path) -> bool:
    """True when a tree_flatten_with_path leaf path ends in a bias-like
    key (bias, BN shift/statistics, peephole weights...)."""
    return _key(path) in BIAS_KEYS


def is_weight_path(path) -> bool:
    """True for parameters eligible for L1/L2 and constraints."""
    return _key(path) not in BIAS_KEYS and _key(path) not in EXCLUDED_KEYS
