"""Shared parameter-key classification.

The framework's parameter trees use short conventional leaf names; several
subsystems (L1/L2 regularization in nn/layers/base.py, weight noise,
constraints) must treat bias-like parameters differently from weights —
this is the single source of truth for that classification (the
reference's analog: ParamInitializer.isBiasParam / isWeightParam,
nn/api/ParamInitializer.java).
"""

BIAS_KEYS = ("b", "vb", "beta", "mean", "var", "pI", "pF", "pO",
             "bmu", "blv", "bout")


def is_bias_path(path) -> bool:
    """True when a tree_flatten_with_path leaf path ends in a bias-like
    key (bias, BN shift/statistics, peephole weights...)."""
    return getattr(path[-1], "key", None) in BIAS_KEYS
