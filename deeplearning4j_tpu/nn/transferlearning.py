"""Transfer learning: fine-tune overrides, freezing, graph surgery.

Analog of deeplearning4j-nn/.../nn/transferlearning/
(TransferLearning.java:34 — Builder with fineTuneConfiguration:73,
setFeatureExtractor:84, nOutReplace:98-160, add/remove layer ops and the
GraphBuilder variant; FineTuneConfiguration.java; TransferLearningHelper
.java for featurize-once training).

Because params here are pytrees keyed by layer name, "surgery + copy
weights" is: edit the layer tuple / node list, rebuild the model, then
copy over every layer whose parameter tree shapes still match.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.config import (
    GlobalConfig,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.optimize.solver import TrainState


class FineTuneConfiguration:
    """Global-hyperparameter overrides applied to every retained layer
    (reference: transferlearning/FineTuneConfiguration.java)."""

    def __init__(self, **overrides):
        # recognized keys: updater, seed, l1, l2, dropout, compute_dtype
        self.overrides = overrides

    class Builder:
        def __init__(self):
            self._o = {}

        def updater(self, u):
            self._o["updater"] = u
            return self

        def seed(self, s: int):
            self._o["seed"] = int(s)
            return self

        def l1(self, v: float):
            self._o["l1"] = float(v)
            return self

        def l2(self, v: float):
            self._o["l2"] = float(v)
            return self

        def dropout(self, v: float):
            self._o["dropout"] = float(v)
            return self

        def compute_dtype(self, dt: str):
            """Activation/compute dtype for the fine-tuned model
            ("bfloat16" for MXU-rate matmuls). Keras-imported models
            arrive float32 (import fidelity); fine-tuning them at bf16
            is the standard TPU recipe — params stay f32, activations
            and matmuls run bf16 (the cast happens at trace time in
            ComputationGraph._walk / MultiLayerNetwork._forward)."""
            self._o["compute_dtype"] = str(dt)
            return self

        def build(self) -> "FineTuneConfiguration":
            return FineTuneConfiguration(**self._o)

    def apply_to_global(self, g: GlobalConfig) -> GlobalConfig:
        kw = {k: v for k, v in self.overrides.items()
              if k in ("updater", "seed", "l1", "l2", "compute_dtype")}
        return dataclasses.replace(g, **kw) if kw else g

    def apply_to_layer(self, layer: Layer) -> Layer:
        kw = {}
        if "dropout" in self.overrides:
            kw["dropout"] = self.overrides["dropout"]
        if "l1" in self.overrides:
            kw["l1"] = self.overrides["l1"]
        if "l2" in self.overrides:
            kw["l2"] = self.overrides["l2"]
        # per-layer updater overrides are cleared so the new global applies
        if "updater" in self.overrides and layer.updater is not None:
            kw["updater"] = None
        return dataclasses.replace(layer, **kw) if kw else layer


def _tree_shapes(t) -> List[tuple]:
    return [tuple(np.shape(a)) for a in jax.tree_util.tree_leaves(t)]


def _copy_matching_params(old_model, new_model,
                          renamed: Optional[Dict[str, str]] = None) -> None:
    """Copy params/model-state for every layer whose tree shapes match."""
    renamed = renamed or {}
    old_p = old_model.train_state.params
    old_s = old_model.train_state.model_state
    new_p = dict(new_model.train_state.params)
    new_s = dict(new_model.train_state.model_state)
    for name in new_p:
        src = renamed.get(name, name)
        if src in old_p and _tree_shapes(old_p[src]) == _tree_shapes(
                new_p[name]):
            # real copies, not references: the train step donates its
            # input buffers, so aliasing would let either model's fit()
            # invalidate the other's params on TPU
            new_p[name] = jax.tree_util.tree_map(jnp.array, old_p[src])
            if src in old_s and _tree_shapes(old_s[src]) == _tree_shapes(
                    new_s.get(name, {})):
                new_s[name] = jax.tree_util.tree_map(jnp.array, old_s[src])
    new_model.train_state = TrainState(
        new_p, new_s, new_model.train_state.opt_state,
        jnp.zeros((), jnp.int32))


def _has_field(layer, field: str) -> bool:
    """True when ``field`` is a real dataclass field — possibly on the
    underlying layer of a wrapper like FrozenLayer, whose __getattr__
    would fool a plain hasattr()."""
    names = {f.name for f in dataclasses.fields(layer)}
    if field in names:
        return True
    under = getattr(layer, "underlying", None)
    return under is not None and _has_field(under, field)


def _replace_fields(layer, **kw):
    """dataclasses.replace that reaches through wrapper layers
    (FrozenLayer.underlying) to the layer that owns the fields."""
    names = {f.name for f in dataclasses.fields(layer)}
    if all(k in names for k in kw):
        return dataclasses.replace(layer, **kw)
    under = getattr(layer, "underlying", None)
    if under is None:
        raise TypeError(f"{type(layer).__name__} has no fields {kw}")
    return dataclasses.replace(layer, underlying=_replace_fields(under, **kw))


class TransferLearning:
    """Namespace matching the reference API: ``TransferLearning.Builder``
    for MultiLayerNetwork, ``TransferLearning.GraphBuilder`` for
    ComputationGraph."""

    class Builder:
        def __init__(self, orig_model):
            if orig_model.train_state is None:
                orig_model.init()
            self._orig = orig_model
            self._layers: List[Layer] = list(orig_model.conf.layers)
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._input_type = orig_model.conf.input_type

        def _index_of(self, layer: Union[int, str]) -> int:
            if isinstance(layer, int):
                return layer
            for i, l in enumerate(self._layers):
                if l.name == layer:
                    return i
            raise KeyError(f"no layer named {layer!r}")

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer: Union[int, str]):
            """Freeze all layers up to and including ``layer``
            (reference: setFeatureExtractor:84)."""
            self._freeze_until = self._index_of(layer)
            return self

        def n_out_replace(self, layer: Union[int, str], n_out: int,
                          weight_init=None):
            """Replace a layer's n_out (re-initialized), fixing up the next
            parametrized layer's n_in (reference: nOutReplace:98-160)."""
            i = self._index_of(layer)
            kw: Dict[str, Any] = {"n_out": int(n_out)}
            if weight_init is not None:
                kw["weight_init"] = weight_init
            self._layers[i] = _replace_fields(self._layers[i], **kw)
            for j in range(i + 1, len(self._layers)):
                nxt = self._layers[j]
                if _has_field(nxt, "n_in"):
                    self._layers[j] = _replace_fields(nxt, n_in=None)
                    break
            return self

        def remove_output_layer(self):
            self._layers.pop()
            return self

        def remove_layers_from_output(self, n: int):
            for _ in range(n):
                self._layers.pop()
            return self

        def add_layer(self, layer: Layer):
            if layer.name is None:
                layer = dataclasses.replace(
                    layer, name=f"layer_{len(self._layers)}")
            self._layers.append(layer)
            return self

        def set_input_type(self, it):
            self._input_type = it
            return self

        def build(self):
            from deeplearning4j_tpu.models.multi_layer_network import (
                MultiLayerNetwork)
            g = self._orig.conf.global_config
            if self._fine_tune is not None:
                g = self._fine_tune.apply_to_global(g)
            layers = []
            for i, l in enumerate(self._layers):
                if self._fine_tune is not None:
                    l = self._fine_tune.apply_to_layer(l)
                if self._freeze_until is not None:
                    l = dataclasses.replace(
                        l, frozen=i <= self._freeze_until)
                layers.append(l)
            conf = MultiLayerConfiguration(
                global_config=g, layers=tuple(layers),
                input_type=self._input_type,
                manual_preprocessors=dict(
                    self._orig.conf.manual_preprocessors))
            conf.resolve_shapes()
            model = MultiLayerNetwork(conf)
            model.init()
            _copy_matching_params(self._orig, model)
            return model

    class GraphBuilder:
        def __init__(self, orig_model):
            if orig_model.train_state is None:
                orig_model.init()
            self._orig = orig_model
            self._nodes = {n.name: n for n in orig_model.conf.nodes}
            self._order = [n.name for n in orig_model.conf.nodes]
            self._inputs = list(orig_model.conf.network_inputs)
            self._input_types = list(orig_model.conf.network_input_types)
            self._outputs = list(orig_model.conf.network_outputs)
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._frozen: set = set()

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, *names: str):
            """Freeze the named vertices and everything upstream of them."""
            frontier = set(names)
            while frontier:
                n = frontier.pop()
                if n in self._frozen or n in self._inputs:
                    continue
                self._frozen.add(n)
                frontier.update(self._nodes[n].inputs)
            return self

        def remove_vertex_and_connections(self, name: str):
            self._nodes.pop(name)
            self._order.remove(name)
            removed_also = [n for n, node in self._nodes.items()
                            if name in node.inputs]
            for n in removed_also:
                self.remove_vertex_and_connections(n)
            self._outputs = [o for o in self._outputs if o in self._nodes]
            return self

        def remove_vertex(self, name: str):
            return self.remove_vertex_and_connections(name)

        def add_layer(self, name: str, layer: Layer, *inputs: str):
            layer = dataclasses.replace(layer, name=name)
            self._nodes[name] = self._node_cls()(
                name=name, inputs=tuple(inputs), layer=layer)
            self._order.append(name)
            return self

        def add_vertex(self, name: str, vertex, *inputs: str):
            self._nodes[name] = self._node_cls()(
                name=name, inputs=tuple(inputs), vertex=vertex)
            self._order.append(name)
            return self

        def n_out_replace(self, name: str, n_out: int, weight_init=None):
            node = self._nodes[name]
            kw: Dict[str, Any] = {"n_out": int(n_out)}
            if weight_init is not None:
                kw["weight_init"] = weight_init
            new_layer = _replace_fields(node.layer, **kw)
            self._nodes[name] = dataclasses.replace(node, layer=new_layer)
            # clear downstream n_in so shape inference recomputes it
            for n, other in self._nodes.items():
                if name in other.inputs and other.layer is not None and \
                        _has_field(other.layer, "n_in"):
                    self._nodes[n] = dataclasses.replace(
                        other, layer=_replace_fields(other.layer, n_in=None))
            return self

        def set_outputs(self, *names: str):
            self._outputs = list(names)
            return self

        @staticmethod
        def _node_cls():
            from deeplearning4j_tpu.nn.graph.config import NodeDef
            return NodeDef

        def build(self):
            from deeplearning4j_tpu.models.computation_graph import (
                ComputationGraph)
            from deeplearning4j_tpu.nn.graph.config import (
                ComputationGraphConfiguration)
            g = self._orig.conf.global_config
            if self._fine_tune is not None:
                g = self._fine_tune.apply_to_global(g)
            nodes = []
            for name in self._order:
                node = self._nodes[name]
                layer = node.layer
                if layer is not None:
                    if self._fine_tune is not None:
                        layer = self._fine_tune.apply_to_layer(layer)
                    # extend, never clear: layers frozen in the original
                    # conf stay frozen
                    if name in self._frozen and not layer.frozen:
                        layer = dataclasses.replace(layer, frozen=True)
                    node = dataclasses.replace(node, layer=layer)
                nodes.append(node)
            conf = ComputationGraphConfiguration(
                global_config=g, network_inputs=tuple(self._inputs),
                network_input_types=tuple(self._input_types),
                nodes=tuple(nodes), network_outputs=tuple(self._outputs))
            conf.resolve()
            model = ComputationGraph(conf)
            model.init()
            _copy_matching_params(self._orig, model)
            return model


class TransferLearningHelper:
    """Featurize-once training (reference: TransferLearningHelper.java):
    run inputs through the frozen front once, then train only the
    unfrozen tail on the cached activations."""

    def __init__(self, model, frozen_boundary: Union[int, str, None] = None):
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        if not isinstance(model, MultiLayerNetwork):
            raise TypeError("TransferLearningHelper currently supports "
                            "MultiLayerNetwork")
        self._orig = model
        layers = model.conf.layers
        if frozen_boundary is None:
            # boundary = last frozen layer
            idx = max((i for i, l in enumerate(layers) if l.frozen),
                      default=-1)
        elif isinstance(frozen_boundary, str):
            idx = [l.name for l in layers].index(frozen_boundary)
        else:
            idx = frozen_boundary
        if idx < 0:
            raise ValueError("model has no frozen layers and no boundary "
                             "was given")
        self._boundary = idx
        # unfrozen tail as its own network. Its input type is layer idx's
        # OUTPUT type (pre-preprocessor — featurize() returns the raw
        # activation), so the tail conf re-infers any boundary
        # preprocessor (e.g. CnnToFeedForward flatten) itself.
        tail_layers = [dataclasses.replace(l, frozen=False)
                       for l in layers[idx + 1:]]
        tail_input = layers[idx].output_type(
            model.conf.layer_input_types()[idx])
        conf = MultiLayerConfiguration(
            global_config=model.conf.global_config,
            layers=tuple(tail_layers), input_type=tail_input)
        conf.resolve_shapes()
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork as MLN)
        self._tail = MLN(conf)
        self._tail.init()
        _copy_matching_params(model, self._tail)

    def unfrozen_mln(self):
        return self._tail

    def featurize(self, dataset: DataSet) -> DataSet:
        acts = self._orig.feed_forward(dataset.features, train=False)
        return DataSet(np.asarray(acts[self._boundary]), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def fit_featurized(self, dataset: DataSet):
        self._tail.fit(dataset)
        # push tail params back into the original model
        new_p = dict(self._orig.train_state.params)
        new_s = dict(self._orig.train_state.model_state)
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        for name in self._tail.train_state.params:
            new_p[name] = copy(self._tail.train_state.params[name])
            if name in self._tail.train_state.model_state:
                new_s[name] = copy(self._tail.train_state.model_state[name])
        self._orig.train_state = self._orig.train_state._replace(
            params=new_p, model_state=new_s)
        return self

    def output_from_featurized(self, featurized):
        return self._tail.output(featurized)
