"""Keras layer → framework layer converters.

Analog of the reference's per-Keras-layer converter classes
(deeplearning4j-modelimport/.../layers/{core,convolutional,pooling,
recurrent,embeddings,normalization,noise}/ and KerasLayer.java:42) plus
the custom-layer registry (KerasLayer.registerCustomLayer:150).

Each converter takes the Keras layer ``config`` dict (+ keras major
version) and returns a ``Converted`` record: our layer/vertex (or a skip
marker for shape-only layers like Flatten — shape adaptation is handled
by this framework's auto-inserted preprocessors), and a ``weights``
function mapping the layer's Keras weight dict to (params, state) trees.

Weight-layout notes (Keras TF backend → this framework, both NHWC):
  Dense kernel [in,out]           → W [in,out]        (identical)
  Conv2D kernel HWIO              → W HWIO            (identical)
  LSTM gate order  i,f,g,o        → ours i,f,o,g      (column permute)
  BatchNorm moving stats          → model_state mean/var
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    ConvolutionMode,
    Convolution1DLayer,
    Cropping2D,
    Deconvolution2D,
    PoolingType,
    SeparableConvolution2D,
    SpaceToDepthLayer,
    SubsamplingLayer,
    Subsampling1DLayer,
    Upsampling1D,
    Upsampling2D,
    ZeroPadding1DLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.dropout import (
    AlphaDropout as SeluAlphaDropout,
    GaussianDropout as GaussianDropoutNoise,
    GaussianNoise as AdditiveGaussianNoise,
)
from deeplearning4j_tpu.nn.layers.feedforward import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingSequenceLayer,
    PermuteLayer,
    ReshapeLayer,
)
from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization,
    LayerNormalization,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.layers.output import GlobalPoolingLayer
from deeplearning4j_tpu.nn.layers.recurrent import (
    Bidirectional,
    LastTimeStep,
    LSTM,
    SimpleRnn,
)
from deeplearning4j_tpu.nn.graph.vertices import (
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.ops.activations import Activation

WeightsFn = Callable[[Dict[str, np.ndarray]], Tuple[dict, dict]]


@dataclasses.dataclass
class Converted:
    layer: Optional[object] = None        # a Layer config
    vertex: Optional[object] = None       # a GraphVertex (merge nodes)
    skip: bool = False                    # shape-only; drop from topology
    weights: Optional[WeightsFn] = None
    # activation the Keras layer carries inline; the final-layer importer
    # uses it to pick the output loss
    activation: Optional[Activation] = None


# ---- helpers -------------------------------------------------------------

_ACTIVATIONS = {
    "linear": Activation.IDENTITY,
    "relu": Activation.RELU,
    "relu6": Activation.RELU6,
    "elu": Activation.ELU,
    "selu": Activation.SELU,
    "gelu": Activation.GELU,
    "sigmoid": Activation.SIGMOID,
    "hard_sigmoid": Activation.HARDSIGMOID,
    "tanh": Activation.TANH,
    "softmax": Activation.SOFTMAX,
    "softplus": Activation.SOFTPLUS,
    "softsign": Activation.SOFTSIGN,
    "swish": Activation.SWISH,
    "silu": Activation.SWISH,
    "mish": Activation.MISH,
    "thresholded_relu": Activation.THRESHOLDEDRELU,
}


def map_activation(name: str) -> Activation:
    if name in ("leaky_relu", "LeakyReLU"):
        # Keras 3's fused string defaults to negative_slope=0.2; the
        # fused Activation.LEAKYRELU enum is fixed at the reference's
        # 0.01 default — importing would be silently wrong on every
        # negative pre-activation. The standalone LeakyReLU LAYER
        # carries its slope and imports exactly.
        raise ValueError(
            "unsupported fused activation 'leaky_relu' (its slope is "
            "not representable in the fused activation enum); use a "
            "standalone keras.layers.LeakyReLU layer instead")
    if name not in _ACTIVATIONS:
        raise ValueError(f"unsupported Keras activation {name!r}")
    return _ACTIVATIONS[name]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def _conv_mode(border: str) -> Tuple[ConvolutionMode, Tuple[int, int]]:
    if border == "same":
        return ConvolutionMode.SAME, (0, 0)
    return ConvolutionMode.TRUNCATE, (0, 0)


def _dense_weights(w: Dict[str, np.ndarray]) -> Tuple[dict, dict]:
    params = {}
    if "kernel" in w:
        params["W"] = w["kernel"]
    elif "W" in w:
        params["W"] = w["W"]
    if "bias" in w:
        params["b"] = w["bias"]
    elif "b" in w:
        params["b"] = w["b"]
    return params, {}


def _bn_weights(w: Dict[str, np.ndarray]) -> Tuple[dict, dict]:
    params = {}
    if "gamma" in w:
        params["gamma"] = w["gamma"]
    if "beta" in w:
        params["beta"] = w["beta"]
    state = {}
    if "moving_mean" in w:
        state["mean"] = w["moving_mean"]
    if "moving_variance" in w:
        state["var"] = w["moving_variance"]
    return params, state


def _lstm_permute(k: np.ndarray) -> np.ndarray:
    """Keras packs gates [i, f, g(c), o]; ours are [i, f, o, g]."""
    h = k.shape[-1] // 4
    i, f, g, o = (k[..., :h], k[..., h:2 * h],
                  k[..., 2 * h:3 * h], k[..., 3 * h:])
    return np.concatenate([i, f, o, g], axis=-1)


def _lstm_weights(w: Dict[str, np.ndarray]) -> Tuple[dict, dict]:
    params = {}
    if "kernel" in w:
        params["Wx"] = _lstm_permute(w["kernel"])
    if "recurrent_kernel" in w:
        params["Wh"] = _lstm_permute(w["recurrent_kernel"])
    if "bias" in w:
        params["b"] = _lstm_permute(w["bias"])
    if "W_i" in w:
        # genuine Keras-1 layout: one matrix per gate (lstm_1_W_i /
        # U_i / b_i, ...); Keras gate letters i,f,c,o → our order i,f,o,c
        params["Wx"] = np.concatenate(
            [w["W_i"], w["W_f"], w["W_o"], w["W_c"]], axis=-1)
        params["Wh"] = np.concatenate(
            [w["U_i"], w["U_f"], w["U_o"], w["U_c"]], axis=-1)
        if "b_i" in w:
            params["b"] = np.concatenate(
                [w["b_i"], w["b_f"], w["b_o"], w["b_c"]], axis=-1)
    return params, {}


def _sep_conv_weights(w: Dict[str, np.ndarray]) -> Tuple[dict, dict]:
    params = {}
    if "depthwise_kernel" in w:
        # Keras (kh, kw, c_in, dm) → our grouped-conv HWIO (kh, kw, 1,
        # c_in*dm)
        dk = w["depthwise_kernel"]
        kh, kw, cin, dm = dk.shape
        params["dW"] = dk.reshape(kh, kw, 1, cin * dm)
    if "pointwise_kernel" in w:
        params["pW"] = w["pointwise_kernel"]
    if "bias" in w:
        params["b"] = w["bias"]
    return params, {}


# ---- converters ----------------------------------------------------------

def _conv_common(cfg: dict) -> dict:
    mode, pad = _conv_mode(cfg.get("padding", cfg.get("border_mode",
                                                      "valid")))
    return dict(
        n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
        kernel_size=_pair(cfg.get("kernel_size",
                                  (cfg.get("nb_row", 1),
                                   cfg.get("nb_col", 1)))),
        stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
        dilation=_pair(cfg.get("dilation_rate", (1, 1))),
        convolution_mode=mode, padding=pad,
        has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))),
    )


def conv2d(cfg, _v):
    act = map_activation(cfg.get("activation", "linear"))
    return Converted(
        layer=ConvolutionLayer(activation=act, **_conv_common(cfg)),
        weights=_dense_weights, activation=act)


def separable_conv2d(cfg, _v):
    act = map_activation(cfg.get("activation", "linear"))
    common = _conv_common(cfg)
    return Converted(
        layer=SeparableConvolution2D(
            activation=act, depth_multiplier=int(
                cfg.get("depth_multiplier", 1)), **common),
        weights=_sep_conv_weights, activation=act)


def _deconv_weights(w: Dict[str, np.ndarray]) -> Tuple[dict, dict]:
    """Keras stores transpose-conv kernels (kh, kw, OUT, IN) in the
    FORWARD-conv orientation (Conv2DTranspose is the gradient of a
    correlation); our Deconvolution2D is a plain correlation on the
    input-dilated tensor, so the kernel maps with the io axes swapped
    AND a spatial rot180 (caught by the k3_conv e2e fixture — unit
    tests never ran real Keras bytes through this path)."""
    params, state = _dense_weights(w)
    if "W" in params and params["W"].ndim == 4:
        params["W"] = np.transpose(params["W"],
                                   (0, 1, 3, 2))[::-1, ::-1].copy()
    return params, state


def conv2d_transpose(cfg, _v):
    act = map_activation(cfg.get("activation", "linear"))
    return Converted(
        layer=Deconvolution2D(activation=act, **_conv_common(cfg)),
        weights=_deconv_weights, activation=act)


def conv1d(cfg, _v):
    """Conv1D / Convolution1D, and Keras-1 AtrousConvolution1D (which
    differs only in carrying dilation as ``atrous_rate`` — reference:
    KerasAtrousConvolution1D.java)."""
    act = map_activation(cfg.get("activation", "linear"))
    mode, _pad = _conv_mode(cfg.get("padding", cfg.get("border_mode",
                                                       "valid")))
    return Converted(
        layer=Convolution1DLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
            kernel_size=int(_first(cfg.get("kernel_size",
                                           cfg.get("filter_length", 1)))),
            stride=int(_first(cfg.get("strides",
                                      cfg.get("subsample_length", 1)))),
            dilation=int(_first(cfg.get("atrous_rate",
                                        cfg.get("dilation_rate", 1)))),
            convolution_mode=mode, activation=act,
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True)))),
        weights=_dense_weights, activation=act)


def _first(v):
    return v[0] if isinstance(v, (list, tuple)) else v


def dense(cfg, _v):
    act = map_activation(cfg.get("activation", "linear"))
    return Converted(
        layer=DenseLayer(
            n_out=int(cfg.get("units", cfg.get("output_dim", 0))),
            activation=act,
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True)))),
        weights=_dense_weights, activation=act)


def _pool(cfg, ptype) -> SubsamplingLayer:
    mode, _ = _conv_mode(cfg.get("padding", cfg.get("border_mode",
                                                    "valid")))
    k = _pair(cfg.get("pool_size", (2, 2)))
    return SubsamplingLayer(
        kernel_size=k, stride=_pair(cfg.get("strides") or k),
        pooling_type=ptype, convolution_mode=mode)


def max_pool2d(cfg, _v):
    return Converted(layer=_pool(cfg, PoolingType.MAX))


def avg_pool2d(cfg, _v):
    return Converted(layer=_pool(cfg, PoolingType.AVG))


def max_pool1d(cfg, _v):
    k = int(_first(cfg.get("pool_size", cfg.get("pool_length", 2))))
    return Converted(layer=Subsampling1DLayer(
        kernel_size=k, stride=int(_first(cfg.get("strides") or k)),
        pooling_type=PoolingType.MAX))


def global_pool(ptype):
    def conv(cfg, _v):
        return Converted(layer=GlobalPoolingLayer(pooling_type=ptype))
    return conv


def batchnorm(cfg, _v):
    return Converted(
        layer=BatchNormalization(
            decay=float(cfg.get("momentum", 0.99)),
            eps=float(cfg.get("epsilon", 1e-3))),
        weights=_bn_weights)


def activation(cfg, _v):
    name = cfg["activation"]
    if name in ("leaky_relu", "LeakyReLU"):
        # the STANDALONE Activation layer can carry the slope exactly
        # (ActivationLayer.alpha) — only the fused-in-Dense string form
        # is unrepresentable (map_activation rejects it)
        return Converted(
            layer=ActivationLayer(activation=Activation.LEAKYRELU,
                                  alpha=float(cfg.get("negative_slope",
                                                      0.2))),
            activation=Activation.LEAKYRELU)
    act = map_activation(name)
    return Converted(layer=ActivationLayer(activation=act), activation=act)


def leaky_relu(cfg, _v):
    """Keras 1/2 carry the slope as ``alpha`` (default 0.3), Keras 3 as
    ``negative_slope`` — dropped entirely before the k3_conv fixture
    caught the 0.3-vs-0.01 divergence."""
    alpha = float(cfg.get("negative_slope", cfg.get("alpha", 0.3)))
    return Converted(layer=ActivationLayer(activation=Activation.LEAKYRELU,
                                           alpha=alpha),
                     activation=Activation.LEAKYRELU)


def dropout(cfg, _v):
    return Converted(layer=DropoutLayer(
        dropout=float(cfg.get("rate", cfg.get("p", 0.5)))))


def embedding(cfg, _v):
    return Converted(
        layer=EmbeddingSequenceLayer(
            n_in=int(cfg.get("input_dim", 0)),
            n_out=int(cfg.get("output_dim", 0))),
        weights=lambda w: ({"W": w.get("embeddings",
                                       next(iter(w.values())))}, {}))


def lstm(cfg, _v):
    act = map_activation(cfg.get("activation", "tanh"))
    gate = map_activation(cfg.get("recurrent_activation",
                                  cfg.get("inner_activation",
                                          "hard_sigmoid")))
    layer = LSTM(n_out=int(cfg.get("units", cfg.get("output_dim", 0))),
                 activation=act, gate_activation=gate)
    if not cfg.get("return_sequences", False):
        layer = LastTimeStep(inner=layer)
        return Converted(layer=layer,
                         weights=lambda w: (_lstm_weights(w)[0], {}))
    return Converted(layer=layer, weights=_lstm_weights)


def simple_rnn(cfg, _v):
    act = map_activation(cfg.get("activation", "tanh"))
    layer = SimpleRnn(n_out=int(cfg.get("units", cfg.get("output_dim", 0))),
                      activation=act)
    def wfn(w):
        params = {}
        if "kernel" in w:
            params["Wx"] = w["kernel"]
        if "recurrent_kernel" in w:
            params["Wh"] = w["recurrent_kernel"]
        if "bias" in w:
            params["b"] = w["bias"]
        return params, {}
    if not cfg.get("return_sequences", False):
        return Converted(layer=LastTimeStep(inner=layer), weights=wfn)
    return Converted(layer=layer, weights=wfn)


def flatten(cfg, _v):
    """Real flatten (ReshapeLayer to 1-D), not a skip: skipping only
    works when the next layer's n_in inference collapses the shape the
    same way, which is true after convs (Cnn→FF preprocessor) but WRONG
    after recurrent/2-D tensors — a Dense after a skipped Flatten of
    (T, F) silently became per-timestep (caught by the k3_merges
    fixture). Row-major like Keras."""
    return Converted(layer=ReshapeLayer(shape=(-1,)))


def reshape(cfg, _v):
    """Keras Reshape honoring target_shape (reference: KerasReshape.java:40
    materializes the target shape — never a silent skip)."""
    target = cfg.get("target_shape")
    if not target:
        raise ValueError("Reshape layer missing target_shape")
    return Converted(layer=ReshapeLayer(shape=tuple(int(d) for d in target)))


def permute(cfg, _v):
    """Keras Permute: real axis transpose of the non-batch dims
    (1-indexed, reference: KerasPermute.java)."""
    dims = cfg.get("dims")
    if not dims:
        raise ValueError("Permute layer missing dims")
    return Converted(layer=PermuteLayer(dims=tuple(int(d) for d in dims)))


def gaussian_noise(cfg, _v):
    """Additive gaussian noise — NOT a dropout (the two regularize
    differently at train time; reference: KerasGaussianNoise.java maps to
    conf/dropout/GaussianNoise)."""
    return Converted(layer=DropoutLayer(
        dropout=AdditiveGaussianNoise(stddev=float(cfg.get("stddev",
                                                           cfg.get("sigma",
                                                                   0.1))))))


def gaussian_dropout(cfg, _v):
    """Multiplicative N(1, rate/(1-rate)) noise (reference:
    KerasGaussianDropout.java → conf/dropout/GaussianDropout)."""
    return Converted(layer=DropoutLayer(
        dropout=GaussianDropoutNoise(rate=float(cfg.get("rate",
                                                        cfg.get("p",
                                                                0.5))))))


def alpha_dropout(cfg, _v):
    """SELU-preserving dropout (reference: KerasAlphaDropout.java →
    conf/dropout/AlphaDropout). Keras' rate is the drop probability,
    same convention as our AlphaDropout.p."""
    return Converted(layer=DropoutLayer(
        dropout=SeluAlphaDropout(p=float(cfg.get("rate", cfg.get("p",
                                                                 0.05))))))


def input_layer(cfg, _v):
    return Converted(skip=True)


def zero_padding2d(cfg, _v):
    p = cfg.get("padding", (1, 1))
    if isinstance(p, (list, tuple)) and p and isinstance(p[0],
                                                         (list, tuple)):
        (pt, pb), (pl, pr) = p
    else:
        (pt, pb) = (pl, pr) = _pair(p)
    return Converted(layer=ZeroPaddingLayer(
        pad=(int(pt), int(pb), int(pl), int(pr))))


def cropping2d(cfg, _v):
    c = cfg.get("cropping", ((0, 0), (0, 0)))
    if isinstance(c[0], (list, tuple)):
        (ct, cb), (cl, cr) = c
    else:
        (ct, cb) = (cl, cr) = _pair(c)
    return Converted(layer=Cropping2D(
        crop=(int(ct), int(cb), int(cl), int(cr))))


def upsampling2d(cfg, _v):
    return Converted(layer=Upsampling2D(size=_pair(cfg.get("size",
                                                           (2, 2)))))


def atrous_conv2d(cfg, _v):
    """Keras-1 AtrousConvolution2D: a Conv2D whose dilation comes from
    ``atrous_rate`` (reference: KerasAtrousConvolution2D.java)."""
    act = map_activation(cfg.get("activation", "linear"))
    common = _conv_common(cfg)
    common["dilation"] = _pair(cfg.get("atrous_rate", (1, 1)))
    return Converted(
        layer=ConvolutionLayer(activation=act, **common),
        weights=_dense_weights, activation=act)


def zero_padding1d(cfg, _v):
    p = cfg.get("padding", 1)
    if isinstance(p, (list, tuple)):
        lo, hi = int(p[0]), int(p[1])
    else:
        lo = hi = int(p)
    return Converted(layer=ZeroPadding1DLayer(pad=(lo, hi)))


def upsampling1d(cfg, _v):
    # Keras 2: "size"; Keras 1: "length"
    return Converted(layer=Upsampling1D(
        size=int(cfg.get("size", cfg.get("length", 2)))))


def space_to_depth(cfg, _v):
    """tf.nn.space_to_depth wrapper layer used by YOLO-family models
    (reference: KerasSpaceToDepth.java)."""
    return Converted(layer=SpaceToDepthLayer(
        block_size=int(cfg.get("block_size", 2))))


def lrn(cfg, _v):
    """Community LRN layer from GoogLeNet-era Keras models (reference:
    custom/KerasLRN.java — registered, not built-in)."""
    return Converted(layer=LocalResponseNormalization(
        k=float(cfg.get("k", 2.0)), n=int(cfg.get("n", 5)),
        alpha=float(cfg.get("alpha", 1e-4)),
        beta=float(cfg.get("beta", 0.75))))


def pool_helper(cfg, _v):
    """GoogLeNet PoolHelper: strips the first row and column to mimic
    caffe's asymmetric pooling (reference: custom/KerasPoolHelper.java →
    PoolHelperVertex)."""
    return Converted(layer=Cropping2D(crop=(1, 0, 1, 0)))


def merge_add(cfg, _v):
    return Converted(vertex=ElementWiseVertex(op="add"))


def merge_sub(cfg, _v):
    return Converted(vertex=ElementWiseVertex(op="subtract"))


def merge_mul(cfg, _v):
    return Converted(vertex=ElementWiseVertex(op="product"))


def merge_avg(cfg, _v):
    return Converted(vertex=ElementWiseVertex(op="average"))


def merge_max(cfg, _v):
    return Converted(vertex=ElementWiseVertex(op="max"))


def concatenate(cfg, _v):
    return Converted(vertex=MergeVertex())


def bidirectional(cfg, v):
    inner_cfg = cfg["layer"]
    inner = convert_layer(inner_cfg["class_name"],
                          inner_cfg["config"], v)
    mode = {"concat": "concat", "sum": "add", "ave": "average",
            "mul": "mul"}.get(cfg.get("merge_mode", "concat"), "concat")
    inner_layer = inner.layer
    if isinstance(inner_layer, LastTimeStep):
        inner_layer = inner_layer.inner   # Bidirectional wraps the RNN itself
    layer = Bidirectional(fwd=inner_layer, mode=mode)

    def wfn(w):
        # direction-qualified keys ("forward_lstm/.../kernel") are the
        # only unambiguous ones — bare leaf aliases collide between
        # directions. Select per direction, then re-leaf for the inner
        # converter (which expects plain "kernel"/"recurrent_kernel").
        def select(tag, other):
            picked = {}
            for k, a in w.items():
                if tag in k and other not in k:
                    picked.setdefault(k.split("/")[-1], a)
            return picked
        fwd = select("forward", "backward")
        bwd = select("backward", "forward")
        fp, _ = inner.weights(fwd) if inner.weights and fwd else ({}, {})
        bp, _ = inner.weights(bwd) if inner.weights and bwd else ({}, {})
        if not fp or not bp:
            raise KeyError(
                "Bidirectional weights missing forward_/backward_ "
                f"qualified entries (available: {sorted(w)})")
        return {"fwd": fp, "bwd": bp}, {}
    return Converted(layer=layer, weights=wfn)


# ---- registry ------------------------------------------------------------

def softmax_layer(cfg, _v):
    axis = cfg.get("axis", -1)
    if axis not in (-1, None):
        raise ValueError(f"unsupported Softmax config: axis={axis} "
                         "(only the feature axis -1 is supported)")
    return Converted(layer=ActivationLayer(activation=Activation.SOFTMAX),
                     activation=Activation.SOFTMAX)


def elu_layer(cfg, _v):
    alpha = float(cfg.get("alpha", 1.0))
    return Converted(layer=ActivationLayer(activation=Activation.ELU,
                                           alpha=alpha),
                     activation=Activation.ELU)


def layer_norm(cfg, _v):
    axis = cfg.get("axis", -1)
    if axis not in (-1, [-1], None):
        raise ValueError(f"unsupported LayerNormalization config: "
                         f"axis={axis} (only the feature axis -1)")
    def _w(w):
        params = {}
        if "gamma" in w:
            params["gamma"] = w["gamma"]
        if "beta" in w:
            params["beta"] = w["beta"]
        return params, {}
    return Converted(
        layer=LayerNormalization(eps=float(cfg.get("epsilon", 1e-3))),
        weights=_w)


def multi_head_attention(cfg, _v):
    """Keras MultiHeadAttention → SelfAttentionLayer. Keras stores per-head
    projections query/key/value kernels [F, H, dh] and output kernel
    [H, dh, F]; ours packs QKV into one [F, 3E] matmul (E = H*dh)."""
    n_heads = int(cfg.get("num_heads", 1))
    key_dim = int(cfg.get("key_dim", 64))
    value_dim = cfg.get("value_dim")
    if value_dim is not None and int(value_dim) != key_dim:
        raise ValueError(
            f"unsupported MultiHeadAttention config: value_dim={value_dim}"
            f" != key_dim={key_dim} (packed-QKV layout needs equal dims)")
    if cfg.get("output_shape") is not None:
        raise ValueError("unsupported MultiHeadAttention config: "
                         "output_shape is not supported")
    axes = cfg.get("attention_axes")
    if isinstance(axes, (list, tuple)):
        axes = list(axes)
    # for rank-3 (N, T, F) input the sequence axis is 1 (== -2)
    if axes not in (None, 1, -2, [1], [-2]):
        raise ValueError(
            f"unsupported MultiHeadAttention config: attention_axes="
            f"{cfg['attention_axes']} (only default sequence-axis "
            "attention)")
    n_out = n_heads * key_dim

    def _w(w):
        def req(name):
            arr = w.get(f"{name}/kernel")
            if arr is None:
                raise KeyError(
                    f"MultiHeadAttention weights missing '{name}/kernel'"
                    f" (available: {sorted(w)})")
            return arr
        q, k, v, o = req("query"), req("key"), req("value"), \
            req("attention_output")
        f = q.shape[0]
        # Keras kernels are (f, h, dh); the framework packs QKV head-major
        # ((head, which, dh) column order — see SelfAttentionLayer) so that
        # tensor parallelism shards whole heads with contiguous tiles.
        def hm(a):
            return a.reshape(f, n_heads, key_dim)
        params = {"Wqkv": np.stack([hm(q), hm(k), hm(v)],
                                   axis=2).reshape(f, -1),
                  "Wo": o.reshape(-1, o.shape[-1])}
        def b2(name):
            return w.get(f"{name}/bias")
        bq, bk, bv = b2("query"), b2("key"), b2("value")
        bo = b2("attention_output")
        if bq is not None and bk is not None and bv is not None:
            params["bqkv"] = np.stack(
                [bq.reshape(n_heads, key_dim), bk.reshape(n_heads, key_dim),
                 bv.reshape(n_heads, key_dim)], axis=1).reshape(-1)
        if bo is not None:
            params["bo"] = bo.reshape(-1)
        return params, {}

    return Converted(
        layer=SelfAttentionLayer(n_out=n_out, n_heads=n_heads,
                                 activation=Activation.IDENTITY),
        weights=_w)


CONVERTERS: Dict[str, Callable[[dict, int], Converted]] = {
    "Dense": dense,
    "Conv2D": conv2d, "Convolution2D": conv2d,
    "SeparableConv2D": separable_conv2d,
    "SeparableConvolution2D": separable_conv2d,
    "Conv2DTranspose": conv2d_transpose,
    "Deconvolution2D": conv2d_transpose,
    "Conv1D": conv1d, "Convolution1D": conv1d,
    "MaxPooling2D": max_pool2d, "AveragePooling2D": avg_pool2d,
    "MaxPooling1D": max_pool1d,
    "GlobalMaxPooling2D": global_pool(PoolingType.MAX),
    "GlobalAveragePooling2D": global_pool(PoolingType.AVG),
    "GlobalMaxPooling1D": global_pool(PoolingType.MAX),
    "GlobalAveragePooling1D": global_pool(PoolingType.AVG),
    "BatchNormalization": batchnorm,
    "LayerNormalization": layer_norm,
    "MultiHeadAttention": multi_head_attention,
    "Softmax": softmax_layer,
    "ELU": elu_layer,
    "Activation": activation,
    "LeakyReLU": leaky_relu,
    "Dropout": dropout, "SpatialDropout2D": dropout,
    "GaussianDropout": gaussian_dropout, "GaussianNoise": gaussian_noise,
    "AlphaDropout": alpha_dropout,
    "Embedding": embedding,
    "LSTM": lstm,
    "SimpleRNN": simple_rnn,
    "Bidirectional": bidirectional,
    "Flatten": flatten, "Reshape": reshape, "Permute": permute,
    "InputLayer": input_layer, "Input": input_layer,
    "ZeroPadding2D": zero_padding2d,
    "ZeroPadding1D": zero_padding1d,
    "Cropping2D": cropping2d,
    "UpSampling2D": upsampling2d,
    "UpSampling1D": upsampling1d,
    "AtrousConvolution2D": atrous_conv2d,
    "AtrousConvolution1D": conv1d,
    "SpaceToDepth": space_to_depth,
    # GoogLeNet-era community layers — the reference requires
    # registerCustomLayer for these; we ship them built-in
    "LRN": lrn, "LRN2D": lrn,
    "PoolHelper": pool_helper,
    "Add": merge_add, "add": merge_add,
    "Subtract": merge_sub, "subtract": merge_sub,
    "Multiply": merge_mul, "multiply": merge_mul,
    "Average": merge_avg, "average": merge_avg,
    "Maximum": merge_max, "maximum": merge_max,
    "Concatenate": concatenate, "concatenate": concatenate,
    "Merge": None,  # resolved by mode in keras.py (Keras 1)
}

_CUSTOM: Dict[str, Callable[[dict, int], Converted]] = {}


def register_custom_layer(class_name: str,
                          converter: Callable[[dict, int], Converted]):
    """Custom-layer hook (reference: KerasLayer.registerCustomLayer:150)."""
    _CUSTOM[class_name] = converter


def convert_layer(class_name: str, cfg: dict, keras_version: int
                  ) -> Converted:
    if class_name in _CUSTOM:
        return _CUSTOM[class_name](cfg, keras_version)
    conv = CONVERTERS.get(class_name)
    if conv is None and class_name == "Merge":
        mode = cfg.get("mode", "concat")
        conv = {"concat": concatenate, "sum": merge_add,
                "mul": merge_mul, "ave": merge_avg,
                "max": merge_max}.get(mode)
    if conv is None:
        raise ValueError(
            f"unsupported Keras layer {class_name!r}; register a converter "
            "with modelimport.register_custom_layer()")
    return conv(cfg, keras_version)
