"""Executable supported-layer manifest (VERDICT r4 #5).

The reference's supported-layer contract lives in
KerasLayer.java's registry + the committed resources of
KerasModelEndToEndTest; here it is executable: ``coverage()`` walks the
COMMITTED fixture corpus (tests/resources/keras), reads each archive's
model config, and maps every supported Keras class name to the e2e
fixtures that exercise it. ``uncovered()`` must stay empty — enforced
by tests/test_keras_fixtures.py::test_registry_fully_covered, so a new
converter cannot land without a fixture.

Alias handling is DERIVED, not hand-maintained: registry names that
dispatch to the same converter function (Keras-1-era spellings,
lowercase functional ops) form one coverage group — a fixture
exercising any member covers them all. The K1 *dialect* config keys
those aliases carry (nb_filter/border_mode/...) are themselves
exercised by the K1 fixtures (k1_mlp, k1_cnn_atrous, k1_lstm,
k1_merge).
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Dict, List, Optional, Set

DEFAULT_FIXTURE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "resources", "keras")


def supported_layers() -> List[str]:
    """Every Keras class name the importer accepts, builtin + custom."""
    from deeplearning4j_tpu.modelimport.layers import _CUSTOM, CONVERTERS
    return sorted(set(CONVERTERS) | set(_CUSTOM))


def _alias_groups() -> Dict[str, Set[str]]:
    """class name → all registry names sharing its converter function."""
    from deeplearning4j_tpu.modelimport.layers import CONVERTERS
    by_fn: Dict[int, Set[str]] = {}
    for name, fn in CONVERTERS.items():
        if fn is None:       # K1 'Merge': mode-resolved, its own group
            continue
        by_fn.setdefault(id(fn), set()).add(name)
    out: Dict[str, Set[str]] = {}
    for group in by_fn.values():
        for name in group:
            out[name] = group
    return out


def _layer_classes(cfg) -> Set[str]:
    out: Set[str] = set()

    def walk(c):
        if isinstance(c, dict):
            cn = c.get("class_name")
            if cn and isinstance(c.get("config"), (dict, list)):
                if cn not in ("Sequential", "Model", "Functional"):
                    out.add(cn)
                walk(c.get("config"))
            else:
                for v in c.values():
                    walk(v)
        elif isinstance(c, (list, tuple)):
            for v in c:
                walk(v)

    walk(cfg)
    return out


def fixture_layer_classes(path: str) -> Set[str]:
    """Class names appearing in one committed fixture archive."""
    with open(path, "rb") as fh:
        magic = fh.read(4)
    if magic == b"PK\x03\x04":                       # .keras zip
        with zipfile.ZipFile(path) as z:
            cfg = json.loads(z.read("config.json"))
    else:                                            # legacy .h5
        import h5py
        with h5py.File(path, "r") as f:
            raw = f.attrs["model_config"]
            if isinstance(raw, bytes):
                raw = raw.decode()
            cfg = json.loads(raw)
    return _layer_classes(cfg)


def _by_class(fixture_dir: str) -> Dict[str, Set[str]]:
    """class name → fixture names containing it, over the corpus dir."""
    by_class: Dict[str, Set[str]] = {}
    for fn in sorted(os.listdir(fixture_dir)):
        if not (fn.endswith(".h5") or fn.endswith(".keras")):
            continue
        name = fn.rsplit(".", 1)[0]
        for cls in fixture_layer_classes(os.path.join(fixture_dir, fn)):
            by_class.setdefault(cls, set()).add(name)
    return by_class


def coverage(fixture_dir: str = DEFAULT_FIXTURE_DIR,
             by_class: Optional[Dict[str, Set[str]]] = None
             ) -> Dict[str, List[str]]:
    """supported class name → sorted fixtures exercising it (directly,
    or via any registry name sharing the converter function).
    ``by_class`` lets callers that already walked the corpus reuse it."""
    if by_class is None:
        by_class = _by_class(fixture_dir)
    groups = _alias_groups()
    out: Dict[str, List[str]] = {}
    for cls in supported_layers():
        names: Set[str] = set()
        for member in groups.get(cls, {cls}):
            names |= by_class.get(member, set())
        out[cls] = sorted(names)
    return out


def uncovered(fixture_dir: str = DEFAULT_FIXTURE_DIR) -> List[str]:
    """Supported class names with NO e2e fixture — the contract is that
    this stays empty."""
    return sorted(cls for cls, fixtures in coverage(fixture_dir).items()
                  if not fixtures)


def render_markdown(fixture_dir: str = DEFAULT_FIXTURE_DIR) -> str:
    """The docs table: every supported layer with its fixture evidence
    (docs render from the same code path the test enforces)."""
    by_class = _by_class(fixture_dir)
    groups = _alias_groups()
    lines = ["| Keras layer | e2e fixtures |", "|---|---|"]
    for cls, fixtures in coverage(fixture_dir, by_class).items():
        note = ""
        if not by_class.get(cls):
            direct = sorted(n for n in groups.get(cls, set())
                            if by_class.get(n))
            if direct:
                note = f" *(alias of {'/'.join(direct)})*"
        lines.append(f"| {cls}{note} | {', '.join(fixtures) or '—'} |")
    return "\n".join(lines)
