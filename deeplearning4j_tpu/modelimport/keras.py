"""KerasModelImport: .h5 file → runnable model.

Analog of the reference's KerasModelImport.java:41 /
KerasModel.java:57 / KerasSequentialModel.java (SURVEY §2.5, §3.5):

    Sequential model config → MultiLayerNetwork
    Functional (Model) config → ComputationGraph

Pipeline: Hdf5Archive reads ``model_config`` JSON + per-layer weight
datasets; each layer goes through the converter registry
(modelimport/layers.py — the KerasLayer registry analog incl. the
custom-layer hook); weights are copied into the initialized model with
layout transposes applied. Dim ordering: TF/NHWC maps 1:1 onto this
framework's native NHWC layouts; Theano dim ordering (DimOrder.THEANO,
KerasLayer.java:47) is handled by transposing conv kernels.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.modelimport.layers import (
    Converted,
    convert_layer,
)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import LossLayer, OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction


def _input_type_from_shape(shape) -> InputType:
    """batch_input_shape (None, ...) → InputType."""
    dims = [d for d in shape[1:]]
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(int(h), int(w), int(c))
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(int(f), None if t is None else int(t))
    if len(dims) == 1:
        return InputType.feed_forward(int(dims[0]))
    raise ValueError(f"unsupported Keras input shape {shape}")


def _loss_for_activation(act: Optional[Activation],
                         keras_loss: Optional[str]) -> LossFunction:
    if keras_loss:
        m = {"categorical_crossentropy": LossFunction.MCXENT,
             "sparse_categorical_crossentropy": LossFunction.MCXENT,
             "binary_crossentropy": LossFunction.XENT,
             "mean_squared_error": LossFunction.MSE,
             "mse": LossFunction.MSE,
             "mean_absolute_error": LossFunction.L1,
             "mae": LossFunction.L1,
             "hinge": LossFunction.HINGE,
             "squared_hinge": LossFunction.SQUARED_HINGE,
             "kullback_leibler_divergence": LossFunction.KL_DIVERGENCE,
             "poisson": LossFunction.POISSON,
             "cosine_proximity": LossFunction.COSINE_PROXIMITY}
        if keras_loss in m:
            return m[keras_loss]
    if act == Activation.SOFTMAX:
        return LossFunction.MCXENT
    if act == Activation.SIGMOID:
        return LossFunction.XENT
    return LossFunction.MSE


def _to_output_layer(layer, act: Optional[Activation],
                     keras_loss: Optional[str]):
    """Final imported layer → trainable output layer (reference:
    KerasModel wires loss layers from training_config)."""
    loss = _loss_for_activation(act, keras_loss)
    if isinstance(layer, DenseLayer) and not isinstance(layer, OutputLayer):
        return OutputLayer(
            n_in=layer.n_in, n_out=layer.n_out, activation=layer.activation,
            has_bias=layer.has_bias, loss=loss)
    return layer


def _training_loss(archive: Hdf5Archive) -> Optional[str]:
    try:
        tc = archive.read_attribute_as_json("training_config")
        loss = tc.get("loss")
        if isinstance(loss, dict):
            loss = next(iter(loss.values()), None)
        if isinstance(loss, dict):  # serialized loss object
            loss = loss.get("class_name")
        return loss if isinstance(loss, str) else None
    except KeyError:
        return None


def _set_imported(model, name: str, conv: Converted,
                  weights: Dict[str, np.ndarray]):
    """Copy one layer's mapped weights into the model's param/state trees,
    shape-checked against the initialized values."""
    if conv.weights is None or not weights:
        return
    params, state = conv.weights(weights)

    def merge(cur, new, path):
        """Recursive merge: nested dicts (Bidirectional fwd/bwd) descend;
        leaves are shape-checked against the initialized values."""
        cur = dict(cur)
        for k, v in new.items():
            if isinstance(v, dict):
                cur[k] = merge(cur.get(k, {}), v, f"{path}/{k}")
                continue
            v = np.asarray(v)
            if k in cur and hasattr(cur[k], "shape") and \
                    tuple(cur[k].shape) != tuple(v.shape):
                raise ValueError(
                    f"imported weight {path}/{k} has shape {v.shape}, "
                    f"model expects {tuple(cur[k].shape)}")
            tgt_dtype = cur[k].dtype if k in cur else jnp.float32
            # copy, never alias: a donated train step after import must
            # not inherit buffers the h5 reader's numpy still owns
            cur[k] = jnp.array(v, tgt_dtype, copy=True)
        return cur

    ts = model.train_state
    new_p = dict(ts.params)
    new_s = dict(ts.model_state)
    if params:
        new_p[name] = merge(new_p.get(name, {}), params, name)
    if state:
        cur = dict(new_s.get(name, {}))
        for k, v in state.items():
            cur[k] = jnp.array(np.asarray(v), jnp.float32, copy=True)
        new_s[name] = cur
    model.train_state = ts._replace(params=new_p, model_state=new_s)


# ---- sequential ----------------------------------------------------------

def import_keras_sequential_model_and_weights(
        path: str, enforce_training_config: bool = False):
    """Sequential .h5 → MultiLayerNetwork (reference:
    KerasModelImport.importKerasSequentialModelAndWeights)."""
    with Hdf5Archive(path) as archive:
        mc = archive.model_config()
        if mc.get("class_name") != "Sequential":
            raise ValueError("not a Sequential model; use "
                             "import_keras_model_and_weights")
        version = archive.keras_version()
        cfg = mc["config"]
        layer_dicts = cfg if isinstance(cfg, list) else cfg["layers"]
        keras_loss = _training_loss(archive)

        input_type = None
        converted: List[Tuple[str, Converted]] = []
        for ld in layer_dicts:
            lcfg = ld["config"]
            if input_type is None:
                shape = lcfg.get("batch_input_shape",
                                 lcfg.get("batch_shape"))
                if shape is not None:
                    input_type = _input_type_from_shape(shape)
            conv = convert_layer(ld["class_name"], lcfg, version)
            converted.append((lcfg.get("name", ld["class_name"]), conv))
        if input_type is None:
            raise ValueError("model config declares no input shape")

        kept = [(n, c) for n, c in converted if not c.skip]
        if not kept:
            raise ValueError("no convertible layers in model")
        # final layer must bear a loss for fit(); reference appends loss
        # layers from training_config
        last_name, last = kept[-1]
        out_layer = _to_output_layer(last.layer, last.activation, keras_loss)
        if out_layer is last.layer and not isinstance(
                last.layer, (OutputLayer, LossLayer)):
            kept.append(("loss", Converted(layer=LossLayer(
                loss=_loss_for_activation(last.activation, keras_loss)))))
        else:
            kept[-1] = (last_name, dataclasses.replace(last,
                                                       layer=out_layer))

        lb = NeuralNetConfiguration.Builder().list()
        for name, conv in kept:
            lb.layer(dataclasses.replace(conv.layer, name=name))
        conf = lb.set_input_type(input_type).build()

        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        model = MultiLayerNetwork(conf).init()
        for name, conv in kept:
            _set_imported(model, name, conv, archive.layer_weights(name))
        return model


# ---- functional ----------------------------------------------------------

def _collect_histories(obj, out: List[str]):
    """Walk a Keras 3 inbound-node args structure, collecting the source
    layer name of every ``__keras_tensor__``."""
    if isinstance(obj, dict):
        if obj.get("class_name") == "__keras_tensor__":
            out.append(obj["config"]["keras_history"][0])
            return
        for v in obj.values():
            _collect_histories(v, out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _collect_histories(v, out)


def _inbound_names(ld: dict) -> List[str]:
    nodes = ld.get("inbound_nodes", [])
    if not nodes:
        return []
    first = nodes[0]
    if isinstance(first, dict):          # Keras 3: {"args": [...tensors]}
        out: List[str] = []
        _collect_histories(first.get("args", []), out)
        return out
    return [n[0] for n in first]         # Keras 1/2: [[name, 0, 0, {}]...]


def _io_layer_names(entry) -> List[str]:
    """input_layers/output_layers: [[name,0,0],...] or single [name,0,0]."""
    if not entry:
        return []
    if isinstance(entry[0], str):
        return [entry[0]]
    return [e[0] for e in entry]


def import_keras_model_and_weights(path: str,
                                   enforce_training_config: bool = False):
    """Functional .h5 → ComputationGraph; Sequential falls through to the
    sequential importer (reference: KerasModelImport
    .importKerasModelAndWeights:50-218)."""
    with Hdf5Archive(path) as archive:
        mc = archive.model_config()
    if mc.get("class_name") == "Sequential":
        return import_keras_sequential_model_and_weights(
            path, enforce_training_config)

    with Hdf5Archive(path) as archive:
        version = archive.keras_version()
        cfg = mc["config"]
        layer_dicts = cfg["layers"]
        keras_loss = _training_loss(archive)
        input_names = _io_layer_names(cfg["input_layers"])
        output_names = _io_layer_names(cfg["output_layers"])

        gb = NeuralNetConfiguration.Builder().graph_builder()
        input_types: Dict[str, InputType] = {}
        converted: Dict[str, Converted] = {}
        renames: Dict[str, str] = {}   # skip-layer name → its input's name

        for ld in layer_dicts:
            name = ld["config"].get("name", ld.get("name"))
            cname = ld["class_name"]
            lcfg = ld["config"]
            if cname == "InputLayer" or name in input_names:
                shape = lcfg.get("batch_input_shape",
                                 lcfg.get("batch_shape"))
                input_types[name] = _input_type_from_shape(shape)
                continue
            conv = convert_layer(cname, lcfg, version)
            inbound = [renames.get(i, i) for i in _inbound_names(ld)]
            if conv.layer is not None and len(set(inbound)) == 1 \
                    and len(inbound) > 1:
                # self-attention style call (mha(x, x)): one source feeds
                # every argument — a single-input layer node here
                inbound = inbound[:1]
            elif cname == "MultiHeadAttention" and len(set(inbound)) > 1:
                raise ValueError(
                    f"unsupported: layer {name!r} is cross-attention "
                    "(distinct query/value sources); only self-attention "
                    "imports are supported")
            if conv.skip:
                if len(inbound) != 1:
                    raise ValueError(
                        f"cannot skip multi-input layer {name}")
                renames[name] = inbound[0]
                continue
            converted[name] = conv
            if conv.vertex is not None:
                gb.add_vertex(name, conv.vertex, *inbound)
            else:
                layer = conv.layer
                if name in output_names:
                    layer = _to_output_layer(layer, conv.activation,
                                             keras_loss)
                    converted[name] = dataclasses.replace(conv, layer=layer)
                gb.add_layer(name, layer, *inbound)

        gb.add_inputs(*input_names)
        gb.set_input_types(*[input_types[n] for n in input_names])
        gb.set_outputs(*[renames.get(n, n) for n in output_names])
        conf = gb.build()

        from deeplearning4j_tpu.models.computation_graph import (
            ComputationGraph)
        model = ComputationGraph(conf).init()
        for name, conv in converted.items():
            _set_imported(model, name, conv, archive.layer_weights(name))
        return model


class KerasModelImport:
    """Static-method namespace matching the reference entry point
    (KerasModelImport.java:41)."""

    importKerasModelAndWeights = staticmethod(
        import_keras_model_and_weights)
    importKerasSequentialModelAndWeights = staticmethod(
        import_keras_sequential_model_and_weights)
    import_keras_model_and_weights = staticmethod(
        import_keras_model_and_weights)
    import_keras_sequential_model_and_weights = staticmethod(
        import_keras_sequential_model_and_weights)
