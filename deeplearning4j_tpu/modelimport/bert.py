"""BERT-base Keras construction + whole-graph import (BASELINE config 3).

The reference's headline Keras-import claim is importing real-world
transformer encoders through KerasModelImport
(deeplearning4j-modelimport/.../KerasModelImport.java:41). This module
builds the full 12-layer BERT-base encoder geometry (hidden 768, 12
heads, FFN 3072, post-LN, learned positions) as a *standard-layer* Keras
functional model — token + position Embedding, MultiHeadAttention, Add,
LayerNormalization, GELU Dense — saves it to HDF5, and imports it
whole-graph into one XLA executable via the ordinary functional-import
path (modelimport/keras.py). Nothing here is BERT-specific in the
importer; this is the e2e proof the converter registry composes to a
real model.

On TPU the imported encoder's attention runs through the Pallas flash
kernel (SelfAttentionLayer → ops/pallas_kernels.attention).
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

import numpy as np

BERT_BASE = dict(vocab=30522, width=768, n_layers=12, n_heads=12,
                 ffn=3072, max_len=512)


def build_keras_bert(vocab: int = 30522, width: int = 768,
                     n_layers: int = 12, n_heads: int = 12,
                     ffn: int = 3072, max_len: int = 512,
                     seq_len: int = 128):
    """Functional Keras BERT-base-geometry encoder.

    Two integer inputs (token ids, position ids) so learned positions use
    the stock Embedding layer; output is the final hidden states.
    """
    import keras
    from keras import layers as L

    ids = keras.Input((seq_len,), name="input_ids")
    pos = keras.Input((seq_len,), name="position_ids")
    tok_e = L.Embedding(vocab, width, name="tok_embed")(ids)
    pos_e = L.Embedding(max_len, width, name="pos_embed")(pos)
    x = L.Add(name="embed_sum")([tok_e, pos_e])
    x = L.LayerNormalization(epsilon=1e-12, name="embed_ln")(x)
    for i in range(n_layers):
        att = L.MultiHeadAttention(num_heads=n_heads,
                                   key_dim=width // n_heads,
                                   name=f"l{i}_mha")(x, x)
        x = L.Add(name=f"l{i}_res1")([x, att])
        x = L.LayerNormalization(epsilon=1e-12, name=f"l{i}_ln1")(x)
        ff = L.Dense(ffn, activation="gelu", name=f"l{i}_ff1")(x)
        ff = L.Dense(width, name=f"l{i}_ff2")(ff)
        x = L.Add(name=f"l{i}_res2")([x, ff])
        x = L.LayerNormalization(epsilon=1e-12, name=f"l{i}_ln2")(x)
    return keras.Model([ids, pos], x, name="bert_base")


def import_bert_base(seq_len: int = 128, h5_path: Optional[str] = None,
                     **overrides):
    """Build BERT-base in Keras, save to HDF5, import whole-graph.

    Returns (our ComputationGraph, the live Keras model). ``overrides``
    shrink the geometry for tests (e.g. vocab=1000, n_layers=2)."""
    from deeplearning4j_tpu.modelimport.keras import (
        import_keras_model_and_weights)
    cfg = dict(BERT_BASE, **overrides)
    km = build_keras_bert(seq_len=seq_len, **cfg)
    cleanup = h5_path is None
    if cleanup:
        fd, h5_path = tempfile.mkstemp(suffix=".h5")
        os.close(fd)
    try:
        km.save(h5_path)
        model = import_keras_model_and_weights(h5_path)
    finally:
        if cleanup:
            os.unlink(h5_path)
    return model, km


def example_inputs(batch: int, seq_len: int, vocab: int,
                   seed: int = 0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (batch, seq_len)).astype(np.float32)
    pos = np.broadcast_to(np.arange(seq_len, dtype=np.float32),
                          (batch, seq_len)).copy()
    return ids, pos
