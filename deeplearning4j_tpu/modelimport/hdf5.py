"""HDF5 archive reader.

Analog of the reference's Hdf5Archive.java (deeplearning4j-modelimport,
which binds libhdf5 via JavaCPP — SURVEY §2.5, §3.5): attribute JSON
reads + dataset traversal over a Keras .h5 file. h5py provides the same
C-library binding surface.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

try:
    import h5py
    _H5PY = True
except ImportError:          # pragma: no cover - h5py is in the image
    _H5PY = False


def _as_str(v) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8")
    return str(v)


class Hdf5Archive:
    """Read-only view of a Keras HDF5 file."""

    def __init__(self, path: str):
        if not _H5PY:
            raise RuntimeError("h5py is required for Keras import")
        self._f = h5py.File(path, "r")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- attributes ------------------------------------------------------
    def read_attribute_as_string(self, name: str, *groups: str) -> str:
        node = self._node(*groups)
        return _as_str(node.attrs[name])

    def read_attribute_as_json(self, name: str, *groups: str):
        return json.loads(self.read_attribute_as_string(name, *groups))

    def has_attribute(self, name: str, *groups: str) -> bool:
        return name in self._node(*groups).attrs

    def read_string_list_attribute(self, name: str, *groups: str
                                   ) -> List[str]:
        return [_as_str(v) for v in self._node(*groups).attrs[name]]

    # ---- datasets --------------------------------------------------------
    def read_data_set(self, name: str, *groups: str) -> np.ndarray:
        return np.asarray(self._node(*groups)[name])

    def get_groups(self, *groups: str) -> List[str]:
        node = self._node(*groups)
        return [k for k in node.keys()
                if isinstance(node[k], h5py.Group)]

    def get_data_sets(self, *groups: str) -> List[str]:
        node = self._node(*groups)
        return [k for k in node.keys()
                if isinstance(node[k], h5py.Dataset)]

    def has_group(self, *groups: str) -> bool:
        try:
            self._node(*groups)
            return True
        except KeyError:
            return False

    def _node(self, *groups: str):
        node = self._f
        for g in groups:
            node = node[g]
        return node

    # ---- Keras-specific helpers -----------------------------------------
    def model_config(self) -> dict:
        return self.read_attribute_as_json("model_config")

    def keras_version(self) -> int:
        """Major Keras version (1 or 2) from the file's attrs."""
        root = ("model_weights",) if self.has_group("model_weights") else ()
        try:
            v = self.read_attribute_as_string("keras_version", *root)
            return int(v.split(".")[0])
        except KeyError:
            return 1

    def layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        """All weights of one layer, keyed by the LAST path component of
        the Keras weight name ('dense_1/kernel:0' → 'kernel')."""
        root = ("model_weights",) if self.has_group("model_weights") else ()
        groups = root + (layer_name,)
        if not self.has_group(*groups):
            return {}
        out: Dict[str, np.ndarray] = {}
        try:
            names = self.read_string_list_attribute("weight_names", *groups)
        except KeyError:
            names = []
        node = self._node(*groups)
        def add_aliases(path_parts, arr):
            # leaf, parent/leaf, and the full path relative to the layer
            # group: deeper aliases disambiguate sublayer weights that
            # share a leaf name (MHA query/kernel vs key/kernel;
            # Bidirectional forward_lstm/... vs backward_lstm/...)
            out[path_parts[-1]] = arr
            if len(path_parts) >= 2:
                out["/".join(path_parts[-2:])] = arr
            if len(path_parts) > 2:
                out["/".join(path_parts)] = arr
            # Keras-1 names carry the layer as a prefix, not a path
            # ("dense_1_W", "lstm_1_W_i"): alias the bare suffix too
            leaf = path_parts[-1]
            if leaf.startswith(layer_name + "_"):
                out[leaf[len(layer_name) + 1:]] = arr

        if names:
            for wname in names:
                arr = np.asarray(node[wname])
                add_aliases(wname.split(":")[0].split("/"), arr)
        else:
            def visit(prefix, n):
                for k in n.keys():
                    item = n[k]
                    if isinstance(item, h5py.Dataset):
                        rel = (prefix + "/" + k.split(":")[0]) \
                            if prefix else k.split(":")[0]
                        add_aliases(rel.split("/"), np.asarray(item))
                    else:
                        visit((prefix + "/" + k) if prefix else k, item)
            visit("", node)
        return out
