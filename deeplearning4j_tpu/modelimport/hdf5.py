"""HDF5 archive reader (legacy .h5 AND Keras-3 .keras zips).

Analog of the reference's Hdf5Archive.java (deeplearning4j-modelimport,
which binds libhdf5 via JavaCPP — SURVEY §2.5, §3.5): attribute JSON
reads + dataset traversal over a Keras .h5 file. h5py provides the same
C-library binding surface.

Beyond the reference: the Keras 3 native ``.keras`` format (a zip of
config.json + metadata.json + model.weights.h5) loads through the same
class — the constructor sniffs the zip magic, reads the config from the
zip, and rebuilds legacy-style weight names from the v3 layout
(``layers/<auto_snake_name>/vars/<i>``, sublayer dirs for MHA/RNN
cells), so every existing converter works unchanged on modern files the
reference cannot read at all.
"""

from __future__ import annotations

import io
import json
import re
import zipfile
from typing import Dict, List, Optional

import numpy as np

try:
    import h5py
    _H5PY = True
except ImportError:          # pragma: no cover - h5py is in the image
    _H5PY = False


def _as_str(v) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8")
    return str(v)


def _snake(name: str) -> str:
    """Keras' to_snake_case (auto layer-path naming in .keras files)."""
    name = re.sub(r"\W+", "", name)
    name = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z])([A-Z])", r"\1_\2", name).lower()


# .keras var index → legacy weight name, per layer class (flag-adjusted
# in _v3_var_names). Order == keras layer.weights order.
_V3_VAR_NAMES = {
    "Dense": ["kernel", "bias"],
    "Conv1D": ["kernel", "bias"],
    "Conv2D": ["kernel", "bias"],
    "Convolution2D": ["kernel", "bias"],
    "Conv2DTranspose": ["kernel", "bias"],
    "DepthwiseConv2D": ["depthwise_kernel", "bias"],
    "SeparableConv2D": ["depthwise_kernel", "pointwise_kernel", "bias"],
    "BatchNormalization": ["gamma", "beta", "moving_mean",
                           "moving_variance"],
    "LayerNormalization": ["gamma", "beta"],
    "Embedding": ["embeddings"],
    "PReLU": ["alpha"],
}

_V3_RNN = {"LSTM", "GRU", "SimpleRNN"}

# Layer classes known to carry NO variables: an empty weight dict is
# legitimate for these and only these. Anything else with a config entry
# but no resolvable weights dir is a layout mismatch — importing random
# init weights silently would violate the refuse-loudly policy.
_V3_STATELESS = {
    "InputLayer", "Dropout", "SpatialDropout1D", "SpatialDropout2D",
    "SpatialDropout3D", "GaussianDropout", "GaussianNoise", "AlphaDropout",
    "Flatten", "Reshape", "Permute", "RepeatVector", "Activation",
    "LeakyReLU", "ELU", "ThresholdedReLU", "ReLU", "Softmax", "Lambda",
    "Masking", "Add", "Subtract", "Multiply", "Average", "Maximum",
    "Minimum", "Concatenate", "Dot", "MaxPooling1D", "MaxPooling2D",
    "MaxPooling3D", "AveragePooling1D", "AveragePooling2D",
    "AveragePooling3D", "GlobalMaxPooling1D", "GlobalMaxPooling2D",
    "GlobalMaxPooling3D", "GlobalAveragePooling1D",
    "GlobalAveragePooling2D", "GlobalAveragePooling3D", "ZeroPadding1D",
    "ZeroPadding2D", "ZeroPadding3D", "Cropping1D", "Cropping2D",
    "Cropping3D", "UpSampling1D", "UpSampling2D", "UpSampling3D",
    "SpaceToDepth", "LRN", "LRN2D", "PoolHelper",
}
_V3_MHA_SUBS = (("query_dense", "query"), ("key_dense", "key"),
                ("value_dense", "value"),
                ("output_dense", "attention_output"))


def _v3_var_names(cls: str, lcfg: dict) -> Optional[List[str]]:
    names = _V3_VAR_NAMES.get(cls)
    if names is None:
        return None
    names = list(names)
    if not lcfg.get("use_bias", True) and "bias" in names:
        names.remove("bias")
    if cls == "BatchNormalization":
        if not lcfg.get("scale", True):
            names.remove("gamma")
        if not lcfg.get("center", True):
            names.remove("beta")
    return names


class Hdf5Archive:
    """Read-only view of a Keras HDF5 file or Keras-3 .keras zip."""

    def __init__(self, path: str):
        if not _H5PY:
            raise RuntimeError("h5py is required for Keras import")
        self._zip_cfg = None
        self._zip_version = None
        with open(path, "rb") as fh:
            magic = fh.read(4)
        if magic == b"PK\x03\x04":
            with zipfile.ZipFile(path) as z:
                self._zip_cfg = json.loads(z.read("config.json"))
                try:
                    meta = json.loads(z.read("metadata.json"))
                    self._zip_version = int(
                        str(meta.get("keras_version", "3")).split(".")[0])
                except KeyError:
                    self._zip_version = 3
                self._f = h5py.File(io.BytesIO(z.read("model.weights.h5")),
                                    "r")
            self._v3_dirs = self._build_v3_dir_map(self._zip_cfg)
        else:
            self._f = h5py.File(path, "r")

    @staticmethod
    def _build_v3_dir_map(cfg: dict) -> Dict[str, dict]:
        """config layer name → (weights dir name, layer dict). Keras
        writes weight dirs under the AUTO path (snake_case class + per-
        base counter, in config order), not the user-visible name."""
        layers = cfg.get("config", {})
        layers = layers.get("layers", []) if isinstance(layers, dict) \
            else []
        counts: Dict[str, int] = {}
        out: Dict[str, dict] = {}
        for ld in layers:
            base = _snake(ld["class_name"])
            n = counts.get(base, 0)
            counts[base] = n + 1
            dirname = base if n == 0 else f"{base}_{n}"
            name = ld.get("config", {}).get("name", dirname)
            out[name] = {"dir": dirname, "layer": ld}
        return out

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- attributes ------------------------------------------------------
    def read_attribute_as_string(self, name: str, *groups: str) -> str:
        node = self._node(*groups)
        return _as_str(node.attrs[name])

    def read_attribute_as_json(self, name: str, *groups: str):
        return json.loads(self.read_attribute_as_string(name, *groups))

    def has_attribute(self, name: str, *groups: str) -> bool:
        return name in self._node(*groups).attrs

    def read_string_list_attribute(self, name: str, *groups: str
                                   ) -> List[str]:
        return [_as_str(v) for v in self._node(*groups).attrs[name]]

    # ---- datasets --------------------------------------------------------
    def read_data_set(self, name: str, *groups: str) -> np.ndarray:
        return np.asarray(self._node(*groups)[name])

    def get_groups(self, *groups: str) -> List[str]:
        node = self._node(*groups)
        return [k for k in node.keys()
                if isinstance(node[k], h5py.Group)]

    def get_data_sets(self, *groups: str) -> List[str]:
        node = self._node(*groups)
        return [k for k in node.keys()
                if isinstance(node[k], h5py.Dataset)]

    def has_group(self, *groups: str) -> bool:
        try:
            self._node(*groups)
            return True
        except KeyError:
            return False

    def _node(self, *groups: str):
        node = self._f
        for g in groups:
            node = node[g]
        return node

    # ---- Keras-specific helpers -----------------------------------------
    def model_config(self) -> dict:
        if self._zip_cfg is not None:
            return self._zip_cfg
        return self.read_attribute_as_json("model_config")

    def keras_version(self) -> int:
        """Major Keras version (1, 2, or 3) from the file."""
        if self._zip_version is not None:
            return self._zip_version
        root = ("model_weights",) if self.has_group("model_weights") else ()
        try:
            v = self.read_attribute_as_string("keras_version", *root)
            return int(v.split(".")[0])
        except KeyError:
            return 1

    # ---- .keras (v3) weight translation ---------------------------------
    def _v3_vars(self, *groups: str) -> List[np.ndarray]:
        if not self.has_group(*groups, "vars"):
            return []
        node = self._node(*groups, "vars")
        return [np.asarray(node[k]) for k in
                sorted(node.keys(), key=lambda s: int(s))]

    @staticmethod
    def _custom_stateless(cls: str, lcfg: dict) -> bool:
        """True when ``cls`` is a user-registered custom layer whose
        converted form carries no params — its weights dir legitimately
        has nothing to load (e.g. a pure-function Lambda-style layer)."""
        from deeplearning4j_tpu.modelimport.layers import (
            _CUSTOM, convert_layer)
        if cls not in _CUSTOM:
            return False
        try:
            conv = convert_layer(cls, lcfg, 3)
        except Exception:
            return False
        return conv.layer is None or not conv.layer.has_params

    def _v3_layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        entry = self._v3_dirs.get(layer_name)
        if entry is None:
            return {}
        cls = entry["layer"]["class_name"]
        lcfg = entry["layer"].get("config", {})
        # 3.x writes "layers/"; some 3.0-era files used
        # "_layer_checkpoint_dependencies/"
        root = ("layers" if self.has_group("layers")
                else "_layer_checkpoint_dependencies")
        if not self.has_group(root, entry["dir"]):
            if cls in _V3_STATELESS or self._custom_stateless(cls, lcfg):
                return {}
            # a weighted layer whose dir can't be found is a layout
            # mismatch (different Keras-3 naming, nested sub-model,
            # shared layer) — importing random init weights silently
            # would be wrong with no error
            raise ValueError(
                f".keras layer {layer_name!r} ({cls}) should carry "
                f"weights but no '{root}/{entry['dir']}' group exists "
                "in model.weights.h5; unsupported .keras layout "
                "(nested sub-model / shared layer / different Keras-3 "
                "naming?)")
        base = (root, entry["dir"])
        out: Dict[str, np.ndarray] = {}

        def put(names, arrs, prefix=""):
            if len(arrs) > len(names):
                # more saved vars than the known layout (LoRA adapters,
                # exotic trackables): importing a truncated subset would
                # be silently WRONG weights — refuse loudly instead
                raise ValueError(
                    f".keras layer {layer_name!r} ({cls}) has "
                    f"{len(arrs)} saved variables but only {len(names)} "
                    f"are understood ({names}); unsupported layer state")
            for n, a in zip(names, arrs):
                # prefixed (multi-sublayer) classes emit ONLY qualified
                # keys: a bare-leaf alias would resolve 'kernel' to the
                # first sublayer's array (MHA query vs key) for any
                # consumer keying by last path component
                out[prefix + n if not prefix else f"{prefix}/{n}"] = a

        if cls == "MultiHeadAttention":
            for sub, alias in _V3_MHA_SUBS:
                put(["kernel", "bias"], self._v3_vars(*base, sub),
                    prefix=alias)
        elif cls in _V3_RNN:
            put(["kernel", "recurrent_kernel", "bias"],
                self._v3_vars(*base, "cell"))
        elif cls == "Bidirectional":
            for sub in ("forward_layer", "backward_layer"):
                put(["kernel", "recurrent_kernel", "bias"],
                    self._v3_vars(*base, sub, "cell"), prefix=sub)
        else:
            arrs = self._v3_vars(*base)
            names = _v3_var_names(cls, lcfg)
            if names is None:
                if len(arrs) == 2 and arrs[1].ndim == 1:
                    names = ["kernel", "bias"]   # generic kernel+bias
                else:
                    names = [f"var_{i}" for i in range(len(arrs))]
            put(names, arrs)
        if not out and cls not in _V3_STATELESS \
                and not self._custom_stateless(cls, lcfg):
            raise ValueError(
                f".keras layer {layer_name!r} ({cls}) should carry "
                "weights but none were found under "
                f"'{root}/{entry['dir']}'; unsupported .keras layout")
        return out

    def layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        """All weights of one layer, keyed by the LAST path component of
        the Keras weight name ('dense_1/kernel:0' → 'kernel')."""
        if self._zip_cfg is not None:
            return self._v3_layer_weights(layer_name)
        root = ("model_weights",) if self.has_group("model_weights") else ()
        groups = root + (layer_name,)
        if not self.has_group(*groups):
            return {}
        out: Dict[str, np.ndarray] = {}
        try:
            names = self.read_string_list_attribute("weight_names", *groups)
        except KeyError:
            names = []
        node = self._node(*groups)
        def add_aliases(path_parts, arr):
            # leaf, parent/leaf, and the full path relative to the layer
            # group: deeper aliases disambiguate sublayer weights that
            # share a leaf name (MHA query/kernel vs key/kernel;
            # Bidirectional forward_lstm/... vs backward_lstm/...)
            out[path_parts[-1]] = arr
            if len(path_parts) >= 2:
                out["/".join(path_parts[-2:])] = arr
            if len(path_parts) > 2:
                out["/".join(path_parts)] = arr
            # Keras-1 names carry the layer as a prefix, not a path
            # ("dense_1_W", "lstm_1_W_i"): alias the bare suffix too
            leaf = path_parts[-1]
            if leaf.startswith(layer_name + "_"):
                out[leaf[len(layer_name) + 1:]] = arr

        if names:
            for wname in names:
                arr = np.asarray(node[wname])
                add_aliases(wname.split(":")[0].split("/"), arr)
        else:
            def visit(prefix, n):
                for k in n.keys():
                    item = n[k]
                    if isinstance(item, h5py.Dataset):
                        rel = (prefix + "/" + k.split(":")[0]) \
                            if prefix else k.split(":")[0]
                        add_aliases(rel.split("/"), np.asarray(item))
                    else:
                        visit((prefix + "/" + k) if prefix else k, item)
            visit("", node)
        return out
