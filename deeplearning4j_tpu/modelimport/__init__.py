"""Keras model import (HDF5).

TPU-native analog of deeplearning4j-modelimport (SURVEY §2.5): read a
Keras .h5 file (model config JSON + weights), convert each Keras layer
through a registry of converters into this framework's layer/vertex
configs, and copy weights into the initialized model. Where the reference
binds libhdf5 through JavaCPP (Hdf5Archive.java), the C HDF5 library is
reached through h5py.
"""

from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.modelimport.keras import (
    KerasModelImport,
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)
from deeplearning4j_tpu.modelimport.layers import register_custom_layer

__all__ = [
    "Hdf5Archive", "KerasModelImport",
    "import_keras_model_and_weights",
    "import_keras_sequential_model_and_weights",
    "register_custom_layer",
]
