"""Broker-fed sample stream for online learning.

One packed frame per micro-batch rides the streaming transport
(streaming/broker.py): features and labels flattened to 2D f32 and
concatenated column-wise, the per-example feature shape carried in the
message key. ``SampleStreamIterator`` turns the topic back into an
unbounded ``DataSetIterator`` that ``fit()`` can consume directly —
the normal AsyncDataSetIterator → DeviceFeeder pipeline handles the
ragged micro-batch sizes recompile-free (bucket normalization), so the
learner never re-traces on stream jitter.

Every Nth consumed micro-batch is diverted into a rolling **holdout
reservoir** (never trained on), which backs the promotion gate's score
calculator via ``holdout_view()`` — a live iterator view that always
reads the current reservoir contents.

Malformed frames (truncated, wrong magic, shape/key disagreement) are
counted on ``dl4j_online_stream_malformed_total`` and skipped; a bad
peer cannot kill the training loop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Iterator, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.streaming.broker import (
    NDArrayConsumer,
    NDArrayPublisher,
    Transport,
)


def pack_samples(features, labels) -> Tuple[np.ndarray, str]:
    """(features, labels) -> one 2D f32 frame + its shape key.

    Rows are examples; columns are flattened features followed by
    flattened labels. The key records the per-example feature shape
    (comma-joined), which is all the consumer needs to split and
    reshape the frame."""
    x = np.asarray(features, dtype=np.float32)  # host-sync-ok: serde boundary, host arrays
    y = np.asarray(labels, dtype=np.float32)  # host-sync-ok: serde boundary, host arrays
    if x.ndim < 1 or y.ndim < 1 or x.shape[0] != y.shape[0]:
        raise ValueError(
            f"features/labels batch mismatch: {x.shape} vs {y.shape}")
    n = x.shape[0]
    packed = np.concatenate(
        [x.reshape(n, -1), y.reshape(n, -1)], axis=1)
    key = ",".join(str(d) for d in x.shape[1:])
    return packed, key


def unpack_samples(packed: np.ndarray, key: str) -> DataSet:
    """Inverse of ``pack_samples``; raises ValueError on a frame whose
    key disagrees with its geometry."""
    arr = np.asarray(packed, dtype=np.float32)  # host-sync-ok: serde boundary, host arrays
    if arr.ndim != 2:
        raise ValueError(f"sample frame must be 2D, got {arr.shape}")
    try:
        feat_shape = tuple(int(d) for d in key.split(",") if d != "")
    except ValueError as e:
        raise ValueError(f"bad sample-frame key {key!r}") from e
    feat_cols = int(np.prod(feat_shape)) if feat_shape else 1
    if feat_cols <= 0 or feat_cols >= arr.shape[1]:
        raise ValueError(
            f"frame key {key!r} ({feat_cols} feature cols) does not "
            f"fit a {arr.shape[1]}-column frame")
    n = arr.shape[0]
    x = arr[:, :feat_cols].reshape((n,) + feat_shape)
    y = arr[:, feat_cols:]
    return DataSet(x, y)


def publish_samples(transport: Transport, topic: str, features,
                    labels) -> None:
    """Publish one micro-batch of training samples to the topic."""
    packed, key = pack_samples(features, labels)
    NDArrayPublisher(transport, topic).publish(packed, key=key)


class HoldoutIterator(DataSetIterator):
    """Live view over the stream's holdout reservoir: each pass merges
    the CURRENT reservoir and re-batches it, so a ScoreCalculator built
    once keeps scoring against fresh holdout data."""

    def __init__(self, stream: "SampleStreamIterator", batch_size: int):
        self.stream = stream
        self._bs = int(batch_size)

    def __iter__(self) -> Iterator[DataSet]:
        merged = self.stream.holdout_snapshot()
        if merged is None:
            return
        n = merged.num_examples()
        for lo in range(0, n, self._bs):
            hi = min(lo + self._bs, n)
            yield DataSet(merged.features[lo:hi], merged.labels[lo:hi])

    @property
    def batch_size(self):
        return self._bs


class SampleStreamIterator(DataSetIterator):
    """Unbounded DataSetIterator over a broker topic.

    ``__iter__`` yields micro-batches until ``stop_event`` is set (or
    ``max_batches`` consumed, when given) — one fit() "epoch" is one
    subscription. ``reset()`` is a no-op: a stream has no beginning to
    rewind to, and fit()'s per-epoch reset must not raise.

    Every ``holdout_every``-th consumed batch is diverted into the
    rolling holdout reservoir (bounded by ``holdout_max`` examples,
    oldest batches evicted) and is NOT yielded for training — the gate
    scores on data the candidate never saw.
    """

    def __init__(self, transport: Transport, topic: str, *,
                 stop_event: Optional[threading.Event] = None,
                 holdout_every: int = 8, holdout_max: int = 512,
                 poll_timeout_s: float = 0.25,
                 max_batches: Optional[int] = None,
                 registry=None):
        if holdout_every < 2:
            raise ValueError("holdout_every must be >= 2 (some batches "
                             "must remain for training)")
        self.consumer = NDArrayConsumer(transport, topic)
        self.topic = topic
        self.stop_event = stop_event if stop_event is not None \
            else threading.Event()
        self.holdout_every = int(holdout_every)
        self.holdout_max = int(holdout_max)
        self.poll_timeout_s = float(poll_timeout_s)  # host-sync-ok: ctor arg
        self.max_batches = max_batches
        # counters below are written by the consuming (async worker)
        # thread and read by promoter/stats threads; plain int writes
        # under the GIL, single-writer
        self.batches_consumed = 0
        self.samples_consumed = 0
        self.malformed = 0
        self.last_sample_walltime: Optional[float] = None
        self._holdout: Deque[DataSet] = deque()
        self._holdout_examples = 0
        self._holdout_lock = threading.Lock()
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = registry if registry is not None else default_registry()
        self._c_samples = reg.counter(
            "dl4j_online_stream_samples_total",
            "training samples consumed off the stream, by topic and "
            "destination (train|holdout)")
        self._c_malformed = reg.counter(
            "dl4j_online_stream_malformed_total",
            "stream frames dropped as malformed (bad serde, key/shape "
            "disagreement), by topic")
        self._c_malformed.inc(0.0, topic=topic)

    # ---- holdout reservoir ----------------------------------------------
    def _add_holdout(self, ds: DataSet):
        with self._holdout_lock:
            self._holdout.append(ds)
            self._holdout_examples += ds.num_examples()
            while (len(self._holdout) > 1
                   and self._holdout_examples > self.holdout_max):
                old = self._holdout.popleft()
                self._holdout_examples -= old.num_examples()

    @property
    def holdout_examples(self) -> int:
        with self._holdout_lock:
            return self._holdout_examples

    def holdout_snapshot(self) -> Optional[DataSet]:
        """Merge the current reservoir into one DataSet (None when
        empty). Copies under the lock, so scoring never races
        eviction."""
        with self._holdout_lock:
            batches = list(self._holdout)
        if not batches:
            return None
        return DataSet.merge(batches)

    def holdout_view(self, batch_size: int = 64) -> HoldoutIterator:
        """A DataSetIterator the earlystopping score calculators can
        hold on to; each pass reads the live reservoir."""
        return HoldoutIterator(self, batch_size)

    # ---- DataSetIterator protocol ---------------------------------------
    def __iter__(self) -> Iterator[DataSet]:
        while not self.stop_event.is_set():
            if (self.max_batches is not None
                    and self.batches_consumed >= self.max_batches):
                return
            try:
                msg = self.consumer.poll(timeout=self.poll_timeout_s)
            except (ConnectionError, OSError):
                # transport retries are exhausted; back off and keep
                # the subscription alive (the broker may come back)
                if self.stop_event.wait(self.poll_timeout_s):
                    return
                continue
            if msg is None:
                continue
            try:
                ds = unpack_samples(msg.array, msg.key)
            except ValueError:
                self.malformed += 1
                self._c_malformed.inc(1.0, topic=self.topic)
                continue
            self.batches_consumed += 1
            self.samples_consumed += ds.num_examples()
            self.last_sample_walltime = time.time()
            if self.batches_consumed % self.holdout_every == 0:
                self._add_holdout(ds)
                self._c_samples.inc(float(ds.num_examples()),  # host-sync-ok: host batch metadata
                                    topic=self.topic, dest="holdout")
                continue
            self._c_samples.inc(float(ds.num_examples()),  # host-sync-ok: host batch metadata
                                topic=self.topic, dest="train")
            yield ds

    def reset(self):
        # unbounded stream: nothing to rewind; fit() calls this at
        # every epoch boundary and it must be a no-op
        pass

    def stop(self):
        self.stop_event.set()

    @property
    def batch_size(self):
        return None
