"""PromotionController: the gated path from candidate params to live
serving.

Each cycle: take a candidate snapshot from the OnlineLearner, score it
on the stream's holdout reservoir through the earlystopping
ScoreCalculator machinery (earlystopping/scorecalc.py), and promote
only a strict improvement — the quant-gate discipline
(evaluation/quant_gate.py): hard precondition, explicit result object,
pass/fail counters. Promotion is ``FleetRouter.promote_params`` — a
param-only hot swap into the warm AOT executables, zero recompiles —
and the pre-swap params/score/p99 baseline is handed to the
RegressionSentinel so a live regression can auto-roll-back.

A candidate whose score is worse, not better by ``min_delta``, NaN, or
unobtainable (scoring raised) is REJECTED and the active version is
untouched; every rejection is counted by reason on
``dl4j_online_rejections_total``.

Scoring runs on a dedicated eval model (a clone) — never on the live
training model (donated params) and never on the serving engines'
committed copies.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, NamedTuple, Optional

from deeplearning4j_tpu.online.learner import Candidate


class PromotionDecision(NamedTuple):
    promoted: bool
    reason: str                   # improved|forced|worse|equal|nan|
    #                               error|no_candidate|no_holdout
    candidate_score: Optional[float]
    active_score: Optional[float]
    version: Optional[str]
    iteration: int
    score_seconds: float
    over_budget: bool


class SwapBaseline(NamedTuple):
    """What the sentinel compares live behavior against."""
    t_swap: float
    version: Optional[str]
    prev_version: Optional[str]
    baseline_score: Optional[float]
    baseline_p99_s: Optional[float]
    minimize: bool


class PromotionController:
    """Scores candidates against the holdout and hot-promotes winners.

    Parameters
    ----------
    router : FleetRouter serving the live pool
    model_name : the pool's name
    learner : OnlineLearner producing candidate snapshots
    score_calculator : earlystopping ScoreCalculator over the holdout
        (its ``minimize_score`` fixes the improvement direction)
    eval_model : a CLONE of the model used only for scoring (its
        train_state is overwritten per evaluation)
    min_delta : required improvement margin; a candidate within
        ``min_delta`` of the active score is rejected as "equal"
    score_budget_s : advisory wall-clock budget for one scoring pass;
        exceeding it flags the decision and the
        ``dl4j_online_score_seconds`` gauge, but does not reject
    interval_s : period of the optional background promotion thread
    sentinel : RegressionSentinel to arm after each promotion
    """

    def __init__(self, router, model_name: str, learner,
                 score_calculator, eval_model, *,
                 min_delta: float = 0.0,
                 score_budget_s: Optional[float] = None,
                 interval_s: float = 5.0,
                 sentinel=None, registry=None):
        self.router = router
        self.model_name = model_name
        self.learner = learner
        self.calc = score_calculator
        self.eval_model = eval_model
        self.min_delta = float(min_delta)  # host-sync-ok: ctor arg
        self.score_budget_s = score_budget_s
        self.interval_s = float(interval_s)  # host-sync-ok: ctor arg
        self.sentinel = sentinel
        self.active_score: Optional[float] = None
        self._prev_active_score: Optional[float] = None
        self.active_walltime: Optional[float] = None   # params trained at
        self.promotions = 0
        self.rejections = 0
        self.last_decision: Optional[PromotionDecision] = None
        self._version_seq = 0
        # promoter state is shared with the sentinel (notify_rollback)
        # and the stats route; one lock covers every mutation
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = registry if registry is not None else default_registry()
        self._c_promotions = reg.counter(
            "dl4j_online_promotions_total",
            "candidate param sets hot-promoted into serving, per model")
        self._c_rejections = reg.counter(
            "dl4j_online_rejections_total",
            "candidates rejected by the promotion gate, per model; "
            "reason=worse|equal|nan|error|no_candidate|no_holdout")
        self._g_candidate = reg.gauge(
            "dl4j_online_candidate_score",
            "holdout score of the most recently evaluated candidate")
        self._g_active = reg.gauge(
            "dl4j_online_active_score",
            "holdout score of the params currently serving")
        self._g_staleness = reg.gauge(
            "dl4j_online_param_staleness_s",
            "age of the serving params: seconds since the active "
            "param set was snapshotted from the learner")
        self._g_score_s = reg.gauge(
            "dl4j_online_score_seconds",
            "wall seconds of the last holdout scoring pass (compare "
            "against the configured score budget)")
        self._c_promotions.inc(0.0, model=model_name)
        self._c_rejections.inc(0.0, model=model_name, reason="worse")

    # ---- scoring ---------------------------------------------------------
    def _load_eval(self, params, model_state):
        import jax
        import jax.numpy as jnp
        ts = self.eval_model.train_state
        self.eval_model.train_state = ts._replace(
            params=jax.tree_util.tree_map(jnp.asarray, params),
            model_state=jax.tree_util.tree_map(jnp.asarray,
                                               model_state))

    def _score(self, params, model_state) -> float:
        self._load_eval(params, model_state)
        return float(  # host-sync-ok: the scoring result fetch IS the promotion gate's one host read
            self.calc.calculate_score(self.eval_model))

    def score_active(self) -> float:
        """Score the params the fleet is serving RIGHT NOW (replica 0's
        committed copy) — the sentinel's live-score probe and the lazy
        initial baseline."""
        pool = self.router.pool(self.model_name)
        with pool.lock:
            engine = pool.engines[0]
        params, mstate = engine.committed_host()
        return self._score(params, mstate)

    def _better(self, cand: float, active: float) -> str:
        """improved | equal | worse under the calculator's direction."""
        delta = (active - cand) if self.calc.minimize_score \
            else (cand - active)
        if delta > self.min_delta:
            return "improved"
        if delta >= -self.min_delta:
            return "equal"
        return "worse"

    # ---- the gate --------------------------------------------------------
    def run_once(self, candidate: Optional[Candidate] = None,
                 force: bool = False) -> PromotionDecision:
        """One promotion cycle. ``force=True`` skips the score
        comparison (NOT the scoring itself) — the benchmark's
        deliberately-degraded-candidate path, exercising the sentinel.
        """
        if candidate is None:
            candidate = self.learner.snapshot()
        self._publish_staleness()
        if candidate is None:
            return self._reject("no_candidate", None, None, 0, 0.0,
                                False)
        if self.learner.stream.holdout_examples == 0:
            return self._reject("no_holdout", None, None,
                                candidate.iteration, 0.0, False)
        t0 = time.perf_counter()
        try:
            cand_score = self._score(candidate.params,
                                     candidate.model_state)
        except Exception:
            dt = time.perf_counter() - t0
            return self._reject("error", None, self.active_score,
                                candidate.iteration, dt,
                                self._over_budget(dt))
        dt = time.perf_counter() - t0
        over = self._over_budget(dt)
        self._g_score_s.set(dt, model=self.model_name)
        self._g_candidate.set(cand_score, model=self.model_name)
        if math.isnan(cand_score) or math.isinf(cand_score):
            return self._reject("nan", cand_score, self.active_score,
                                candidate.iteration, dt, over)
        with self._lock:
            if self.active_score is None:
                # first cycle: baseline = the params serving today,
                # scored on the same holdout
                self.active_score = self.score_active()
                self._g_active.set(self.active_score,
                                   model=self.model_name)
        if not force:
            verdict = self._better(cand_score, self.active_score)
            if verdict != "improved":
                return self._reject(verdict, cand_score,
                                    self.active_score,
                                    candidate.iteration, dt, over)
        return self._promote(candidate, cand_score,
                             "forced" if force else "improved", dt,
                             over)

    def _over_budget(self, dt: float) -> bool:
        return (self.score_budget_s is not None
                and dt > self.score_budget_s)

    def _reject(self, reason: str, cand_score, active_score,
                iteration: int, dt: float,
                over: bool) -> PromotionDecision:
        self._c_rejections.inc(1.0, model=self.model_name,
                               reason=reason)
        with self._lock:
            self.rejections += 1
            d = PromotionDecision(False, reason, cand_score,
                                  active_score, None, iteration, dt,
                                  over)
            self.last_decision = d
        return d

    def _promote(self, candidate: Candidate, cand_score: float,
                 reason: str, dt: float, over: bool
                 ) -> PromotionDecision:
        pool = self.router.pool(self.model_name)
        # baseline BEFORE the swap: promote_params resets the pool ring,
        # so these are the last pre-swap latencies
        q = pool.ring.quantiles((0.99,))
        baseline_p99 = q.get(0.99)
        with self._lock:
            prev_score = self.active_score
            prev_version = pool.active_version
            self._version_seq += 1
            version = f"online-{self._version_seq}" \
                      f"-it{candidate.iteration}"
        self.router.promote_params(self.model_name, candidate.params,
                                   candidate.model_state,
                                   version=version)
        with self._lock:
            self._prev_active_score = prev_score
            self.active_score = cand_score
            self.active_walltime = candidate.walltime
            self.promotions += 1
            d = PromotionDecision(True, reason, cand_score, prev_score,
                                  version, candidate.iteration, dt,
                                  over)
            self.last_decision = d
        self._c_promotions.inc(1.0, model=self.model_name)
        self._g_active.set(cand_score, model=self.model_name)
        self._publish_staleness()
        if self.sentinel is not None:
            self.sentinel.observe_swap(SwapBaseline(
                t_swap=time.time(), version=version,
                prev_version=prev_version,
                baseline_score=prev_score,
                baseline_p99_s=baseline_p99,
                minimize=self.calc.minimize_score))
        return d

    def notify_rollback(self):
        """Sentinel hook: the promotion was reverted — restore the
        pre-promotion score as the active baseline."""
        with self._lock:
            if self._prev_active_score is not None:
                self.active_score = self._prev_active_score
                self._g_active.set(self.active_score,
                                   model=self.model_name)
            self.active_walltime = None

    def _publish_staleness(self):
        if self.active_walltime is not None:
            self._g_staleness.set(time.time() - self.active_walltime,
                                  model=self.model_name)

    # ---- background loop -------------------------------------------------
    def start(self) -> "PromotionController":
        if self._thread is not None:
            raise RuntimeError("PromotionController already started")
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:
                    # a scoring/promotion hiccup must not kill the
                    # promotion loop; the next cycle retries
                    pass

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="online-promoter")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            d = self.last_decision
            return {
                "promotions": self.promotions,
                "rejections": self.rejections,
                "active_score": self.active_score,
                "staleness_s": (time.time() - self.active_walltime
                                if self.active_walltime else None),
                "last_decision": None if d is None else {
                    "promoted": d.promoted, "reason": d.reason,
                    "candidate_score": d.candidate_score,
                    "active_score": d.active_score,
                    "version": d.version,
                    "iteration": d.iteration,
                    "score_seconds": d.score_seconds,
                    "over_budget": d.over_budget,
                },
            }
