"""OnlineLearner: incremental fit() over an unbounded sample stream.

The learner owns the TRAINING model and a background thread running
``model.fit(stream, epochs=1)`` — one "epoch" is the whole stream
subscription, terminated by the stream's stop event. The normal fit
pipeline applies unchanged: AsyncDataSetIterator prefetch, DeviceFeeder
staging, listeners, flight recorder.

Candidate snapshots are the promotion gate's input and the one place
thread discipline really bites: the train step DONATES its params
(optimize/solver.py), and on the CPU backend device buffers zero-copy
alias host memory — reading ``model.train_state`` from another thread
can catch a donated/garbage buffer mid-step. ``snapshot()`` therefore
never touches the train state from the calling thread while training
is live: it posts a request that the learner thread itself services
BETWEEN steps (a TrainingListener hook), copying params to fresh host
arrays. Only when the learner thread is not running does ``snapshot()``
copy inline.
"""

from __future__ import annotations

import threading
import time
from typing import Any, NamedTuple, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


class Candidate(NamedTuple):
    """One promotable parameter snapshot (host numpy copies)."""
    params: Any
    model_state: Any
    iteration: int
    samples_seen: int
    walltime: float


def _host_copy(tree):
    """Deep host copy of a param tree — ``np.array`` copies, never
    views (CPU ``device_get`` can alias live donated buffers)."""
    import jax
    return jax.tree_util.tree_map(
        lambda a: np.array(a, copy=True), jax.device_get(tree))


class _SnapshotListener(TrainingListener):
    """Services snapshot requests on the learner thread, between
    dispatched steps — the only point where ``train_state`` is
    guaranteed stable and un-donated."""

    def __init__(self, learner: "OnlineLearner"):
        self.learner = learner

    def iteration_done(self, model, iteration, epoch, loss, etl_ms,
                       batch_size):
        lr = self.learner
        lr._iterations = iteration
        if not lr._snap_req.is_set():
            return
        lr._snap_result = Candidate(
            params=_host_copy(model.train_state.params),
            model_state=_host_copy(model.train_state.model_state),
            iteration=iteration,
            samples_seen=lr.stream.samples_consumed,
            walltime=time.time())
        lr._snap_req.clear()
        lr._snap_done.set()


class OnlineLearner:
    """Drives incremental training off a SampleStreamIterator."""

    def __init__(self, model, stream, *, prefetch: Optional[int] = None,
                 k_steps: Optional[int] = None):
        self.model = model
        self.stream = stream
        self.prefetch = prefetch
        self.k_steps = k_steps
        self._thread: Optional[threading.Thread] = None
        self._iterations = 0
        self.error: Optional[BaseException] = None
        # snapshot handshake: one request in flight at a time
        self._snap_lock = threading.Lock()
        self._snap_req = threading.Event()
        self._snap_done = threading.Event()
        self._snap_result: Optional[Candidate] = None
        model.add_listeners(_SnapshotListener(self))

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "OnlineLearner":
        if self._thread is not None:
            raise RuntimeError("OnlineLearner already started")

        def run():
            try:
                self.model.fit(self.stream, epochs=1,
                               prefetch=self.prefetch,
                               k_steps=self.k_steps)
            except BaseException as e:
                self.error = e
            finally:
                # a blocked snapshot() must not hang on a dead learner
                if self._snap_req.is_set():
                    self._snap_req.clear()
                    self._snap_done.set()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="online-learner")
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0):
        self.stream.stop()
        t = self._thread
        if t is not None:
            t.join(timeout)
        if self.error is not None:
            raise self.error

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def iterations(self) -> int:
        return self._iterations

    # ---- candidate snapshots --------------------------------------------
    def snapshot(self, timeout: float = 5.0) -> Optional[Candidate]:
        """Host-copied candidate params, taken between train steps.

        Returns None when the learner is live but no step completed
        within ``timeout`` (idle stream — nothing new to promote
        anyway). Raises the learner thread's error if training died."""
        if self.error is not None:
            raise self.error
        if not self.alive:
            # no concurrent stepper: safe to copy inline
            if self.model.train_state is None:
                return None
            return Candidate(
                params=_host_copy(self.model.train_state.params),
                model_state=_host_copy(
                    self.model.train_state.model_state),
                iteration=self._iterations,
                samples_seen=self.stream.samples_consumed,
                walltime=time.time())
        with self._snap_lock:
            self._snap_done.clear()
            self._snap_result = None
            self._snap_req.set()
            if not self._snap_done.wait(timeout):
                self._snap_req.clear()
                return None
            return self._snap_result
