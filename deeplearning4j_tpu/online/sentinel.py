"""RegressionSentinel: post-promotion watchdog with auto-rollback.

The promotion gate scores a candidate on holdout data BEFORE the swap;
the sentinel watches what actually happens AFTER — live fleet latency
(the pool's LatencyRing, which ``promote_params`` reset at the swap, so
every observation is post-swap) and the served params' holdout score
(re-scored live, which also catches in-place corruption). On a
regression it rolls the pool back to the bitwise param standby via
``FleetRouter.rollback_params``, counts it on
``dl4j_online_rollbacks_total{reason=p99|score|nan}``, and drops a
flight-recorder breadcrumb so the next crash dump carries the story.

The p99 probe reads ``pool.ring.quantiles()`` (the full post-reset
window) — NOT ``delta_quantiles()``, whose mark is owned by the fleet's
AIMD shed controller; a second delta reader would steal its
observations.

A baseline that survives ``window_s`` without tripping is retired: the
promotion stands and the sentinel goes idle until the next swap.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Optional

from deeplearning4j_tpu.online.promoter import SwapBaseline


class RegressionSentinel:
    """Watches one pool after each param promotion.

    Parameters
    ----------
    router / model_name : the pool to watch and roll back
    score_fn : zero-arg callable re-scoring the LIVE committed params
        on the holdout (``PromotionController.score_active``); None
        disables the score probe
    p99_factor : live p99 over ``baseline_p99 * factor`` is a
        regression (only after ``min_requests`` post-swap requests)
    p99_floor_s : absolute p99 the live value must also exceed — a
        factor alone would trip on micro-latency noise
    score_delta : tolerated live-score slack vs the pre-swap baseline
    min_requests : post-swap request count before the p99 rule arms
    window_s : how long after a swap the sentinel keeps watching
    on_rollback : callable(reason) fired after a rollback (the
        promoter's ``notify_rollback`` rides here)
    """

    def __init__(self, router, model_name: str, *,
                 score_fn: Optional[Callable[[], float]] = None,
                 p99_factor: float = 3.0, p99_floor_s: float = 0.050,
                 score_delta: float = 0.0, min_requests: int = 20,
                 window_s: float = 30.0, poll_s: float = 0.5,
                 on_rollback: Optional[Callable[[str], None]] = None,
                 registry=None):
        self.router = router
        self.model_name = model_name
        self.score_fn = score_fn
        self.p99_factor = float(p99_factor)  # host-sync-ok: ctor arg
        self.p99_floor_s = float(p99_floor_s)  # host-sync-ok: ctor arg
        self.score_delta = float(score_delta)  # host-sync-ok: ctor arg
        self.min_requests = int(min_requests)
        self.window_s = float(window_s)  # host-sync-ok: ctor arg
        self.poll_s = float(poll_s)  # host-sync-ok: ctor arg
        self.on_rollback = on_rollback
        self.rollbacks = 0
        self.last_rollback_reason: Optional[str] = None
        self._baseline: Optional[SwapBaseline] = None
        self._count_at_swap = 0
        # baseline handoff: promoter thread writes, sentinel/bench
        # threads read-modify in check()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = registry if registry is not None else default_registry()
        self._c_rollbacks = reg.counter(
            "dl4j_online_rollbacks_total",
            "automatic post-promotion rollbacks, per model; reason="
            "p99 (latency regression) | score (holdout regression) | "
            "nan (non-finite live score)")
        self._c_rollbacks.inc(0.0, model=model_name, reason="score")

    # ---- baseline handoff ------------------------------------------------
    def observe_swap(self, baseline: SwapBaseline):
        """Arm the sentinel for a fresh promotion (promoter calls this
        right after ``promote_params``; the pool ring is already
        reset)."""
        pool = self.router.pool(self.model_name)
        with self._lock:
            self._baseline = baseline
            self._count_at_swap = pool.ring.count

    @property
    def watching(self) -> bool:
        with self._lock:
            return self._baseline is not None

    # ---- the verdict -----------------------------------------------------
    def _regression(self, baseline: SwapBaseline) -> Optional[str]:
        pool = self.router.pool(self.model_name)
        # p99 rule: enough post-swap traffic, live p99 over both the
        # relative and the absolute bar
        served = pool.ring.count - self._count_at_swap
        if served >= self.min_requests \
                and baseline.baseline_p99_s is not None:
            q = pool.ring.quantiles((0.99,))
            live_p99 = q.get(0.99)
            if live_p99 is not None \
                    and live_p99 > self.p99_floor_s \
                    and live_p99 > baseline.baseline_p99_s \
                    * self.p99_factor:
                return "p99"
        # score rule: the LIVE committed params re-scored on holdout
        if self.score_fn is not None \
                and baseline.baseline_score is not None:
            try:
                live = float(self.score_fn())  # host-sync-ok: the live-score probe is a deliberate host read off the dispatch path
            except Exception:
                return None   # holdout hiccup is not a regression
            if math.isnan(live) or math.isinf(live):
                return "nan"
            slack = (live - baseline.baseline_score) if baseline.minimize \
                else (baseline.baseline_score - live)
            if slack > self.score_delta:
                return "score"
        return None

    def check(self) -> Optional[str]:
        """One sentinel pass: returns the rollback reason when a
        regression fired, None otherwise (including idle / survived)."""
        with self._lock:
            baseline = self._baseline
        if baseline is None:
            return None
        reason = self._regression(baseline)
        if reason is None:
            if time.time() - baseline.t_swap > self.window_s:
                # survived the watch window: the promotion stands
                with self._lock:
                    if self._baseline is baseline:
                        self._baseline = None
            return None
        self._rollback(baseline, reason)
        return reason

    def _rollback(self, baseline: SwapBaseline, reason: str):
        self.router.rollback_params(self.model_name)
        with self._lock:
            self.rollbacks += 1
            self.last_rollback_reason = reason
            if self._baseline is baseline:
                self._baseline = None
        self._c_rollbacks.inc(1.0, model=self.model_name,
                              reason=reason)
        from deeplearning4j_tpu.observe.flight_recorder import (
            default_flight_recorder)
        rec = default_flight_recorder()
        if rec is not None:
            rec.note(f"online_rollback_{self.model_name}", {
                "reason": reason,
                "rolled_back_version": baseline.version,
                "restored_version": baseline.prev_version,
                "baseline_score": baseline.baseline_score,
                "baseline_p99_s": baseline.baseline_p99_s,
            })
        if self.on_rollback is not None:
            self.on_rollback(reason)

    # ---- background loop -------------------------------------------------
    def start(self) -> "RegressionSentinel":
        if self._thread is not None:
            raise RuntimeError("RegressionSentinel already started")
        self._stop.clear()

        def run():
            while not self._stop.wait(self.poll_s):
                try:
                    self.check()
                except Exception:
                    # a probe hiccup must not kill the watchdog
                    pass

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="online-sentinel")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "watching": self._baseline is not None,
                "rollbacks": self.rollbacks,
                "last_rollback_reason": self.last_rollback_reason,
            }
