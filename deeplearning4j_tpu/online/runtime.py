"""OnlineServing: train-and-serve in one process.

The orchestrator that wires the online-learning subsystem together —
one call builds the whole loop:

- a **serving clone** of the model behind a FleetRouter pool (warm AOT
  bucket ladder, admission control);
- a **SampleStreamIterator** subscribed to the broker topic, feeding
- the **OnlineLearner** incrementally fitting the TRAINING model;
- a **PromotionController** scoring candidate snapshots on the
  stream's holdout and hot-promoting improvements (param-only swap,
  zero recompiles); and
- a **RegressionSentinel** watching post-swap telemetry, rolling back
  to the bitwise standby on live regressions.

Three model copies exist on purpose (CPU zero-copy + donation: the
train step donates params, so serving/eval must never alias them):
the caller's model trains, ``clone()`` #1 serves, ``clone()`` #2 is
the promoter's scoring scratchpad.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from deeplearning4j_tpu.earlystopping.scorecalc import (
    DataSetLossCalculator,
)
from deeplearning4j_tpu.online.learner import OnlineLearner
from deeplearning4j_tpu.online.promoter import PromotionController
from deeplearning4j_tpu.online.sentinel import RegressionSentinel
from deeplearning4j_tpu.online.stream import SampleStreamIterator
from deeplearning4j_tpu.parallel.fleet import FleetRouter


class OnlineServing:
    """One-process train-and-serve runtime over a broker-fed stream."""

    def __init__(self, model, transport, *, topic: str = "train",
                 model_name: str = "online",
                 feature_shape=None, batch_limit: int = 32,
                 pool_size: int = 1, slo_ms: Optional[float] = None,
                 holdout_every: int = 8, holdout_max: int = 512,
                 holdout_batch: int = 64,
                 promote_interval_s: float = 5.0,
                 min_delta: float = 0.0,
                 score_budget_s: Optional[float] = None,
                 rollback_p99_factor: float = 3.0,
                 rollback_p99_floor_s: float = 0.050,
                 rollback_score_delta: float = 0.0,
                 sentinel_window_s: float = 30.0,
                 sentinel_poll_s: float = 0.5,
                 router: Optional[FleetRouter] = None,
                 registry=None, **engine_kwargs):
        if model.train_state is None:
            model.init()
        self.model = model
        self.model_name = model_name
        # serving and eval copies: deep clones, never aliases of the
        # donated training params
        serving_model = model.clone()
        eval_model = model.clone()
        self.router = router if router is not None else FleetRouter(
            slo_ms=slo_ms, registry=registry)
        self.pool = self.router.add_pool(
            model_name, serving_model, version="v0",
            pool_size=pool_size, slo_ms=slo_ms,
            feature_shape=feature_shape, batch_limit=batch_limit,
            **engine_kwargs)
        self.stream = SampleStreamIterator(
            transport, topic, holdout_every=holdout_every,
            holdout_max=holdout_max, registry=registry)
        self.learner = OnlineLearner(model, self.stream)
        calc = DataSetLossCalculator(
            self.stream.holdout_view(holdout_batch))
        self.sentinel = RegressionSentinel(
            self.router, model_name,
            p99_factor=rollback_p99_factor,
            p99_floor_s=rollback_p99_floor_s,
            score_delta=rollback_score_delta,
            window_s=sentinel_window_s, poll_s=sentinel_poll_s,
            registry=registry)
        self.promoter = PromotionController(
            self.router, model_name, self.learner, calc, eval_model,
            min_delta=min_delta, score_budget_s=score_budget_s,
            interval_s=promote_interval_s, sentinel=self.sentinel,
            registry=registry)
        # close the loop: the sentinel probes the LIVE committed params
        # with the promoter's scorer, and a rollback restores the
        # promoter's baseline
        self.sentinel.score_fn = self.promoter.score_active
        self.sentinel.on_rollback = \
            lambda reason: self.promoter.notify_rollback()
        self._started = False
        self._lock = threading.Lock()

    # ---- lifecycle -------------------------------------------------------
    def start(self, *, background_promotion: bool = True
              ) -> "OnlineServing":
        with self._lock:
            if self._started:
                raise RuntimeError("OnlineServing already started")
            self._started = True
        self.learner.start()
        if background_promotion:
            self.promoter.start()
            self.sentinel.start()
        return self

    def stop(self, timeout: float = 30.0):
        self.promoter.stop()
        self.sentinel.stop()
        try:
            self.learner.stop(timeout)
        finally:
            self.router.shutdown()

    # the CLI's serve front door calls shutdown() on whatever it built
    shutdown = stop

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # ---- serving passthrough ---------------------------------------------
    def submit(self, features):
        return self.router.submit(features, model=self.model_name)

    def output(self, features):
        return self.router.output(features, model=self.model_name)

    def promote_params(self, params, model_state=None, *,
                       version: Optional[str] = None):
        """Hot-swap externally refreshed params into the warm serving
        pool (FleetRouter.promote_params: structure-validated,
        param-only, zero recompiles) — the path for weights trained
        OUTSIDE the broker-fed learner, e.g. embeddings refreshed by
        ``Word2Vec.fit_stream`` from a corpus stream. Bypasses the
        gated promoter deliberately: the caller owns quality gating."""
        return self.router.promote_params(self.model_name, params,
                                          model_state, version=version)

    # ---- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "model": self.model_name,
            "learner": {
                "alive": self.learner.alive,
                "iterations": self.learner.iterations,
            },
            "stream": {
                "topic": self.stream.topic,
                "batches": self.stream.batches_consumed,
                "samples": self.stream.samples_consumed,
                "malformed": self.stream.malformed,
                "holdout_examples": self.stream.holdout_examples,
            },
            "promotion": self.promoter.stats(),
            "sentinel": self.sentinel.stats(),
            "pool": self.pool.stats(),
        }
