"""Online learning: train-and-serve in one process with gated hot
promotion (ROADMAP item 5).

- ``stream``   — broker-fed unbounded DataSetIterator + holdout
- ``learner``  — OnlineLearner: incremental fit off the stream
- ``promoter`` — PromotionController: holdout-gated param hot swap
- ``sentinel`` — RegressionSentinel: post-swap watchdog + rollback
- ``runtime``  — OnlineServing: the wired-together orchestrator
"""

from deeplearning4j_tpu.online.learner import Candidate, OnlineLearner
from deeplearning4j_tpu.online.promoter import (
    PromotionController,
    PromotionDecision,
    SwapBaseline,
)
from deeplearning4j_tpu.online.runtime import OnlineServing
from deeplearning4j_tpu.online.sentinel import RegressionSentinel
from deeplearning4j_tpu.online.stream import (
    HoldoutIterator,
    SampleStreamIterator,
    pack_samples,
    publish_samples,
    unpack_samples,
)

__all__ = [
    "Candidate",
    "HoldoutIterator",
    "OnlineLearner",
    "OnlineServing",
    "PromotionController",
    "PromotionDecision",
    "RegressionSentinel",
    "SampleStreamIterator",
    "SwapBaseline",
    "pack_samples",
    "publish_samples",
    "unpack_samples",
]
