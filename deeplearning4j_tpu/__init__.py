"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A brand-new framework with the capabilities of Deeplearning4j (reference:
codeinvento/deeplearning4j), designed TPU-first on JAX/XLA/Pallas:

- configuration-driven sequential (``MultiLayerNetwork``) and DAG
  (``ComputationGraph``) models compiled to single XLA executables,
- pure-functional layers differentiated with ``jax.grad`` (no hand-written
  backward passes — the reference pairs ``activate``/``backpropGradient`` by
  hand, e.g. deeplearning4j-nn/.../nn/api/Layer.java:88),
- optimizers as pure update transforms over parameter pytrees,
- SPMD parallelism over ``jax.sharding.Mesh`` axes (data/model/pipeline)
  instead of the reference's threaded ParallelWrapper + Spark/Aeron stack.

Public API intentionally mirrors DL4J naming so a DL4J user can find their
way around: ``NeuralNetConfiguration``, ``MultiLayerConfiguration``,
``ComputationGraphConfiguration``, ``MultiLayerNetwork``, ``ComputationGraph``,
``ParallelWrapper``, ``Evaluation``, ``EarlyStoppingConfiguration``, etc.
"""

def _wire_persistent_compile_cache():
    """Point JAX's persistent compilation cache at a per-user directory
    (VERDICT r3 #6: the 25-60 s cold XLA compile of the big embedding /
    conv steps should be paid once per MACHINE, not per process).
    Opt-out with DL4J_COMPILE_CACHE=off; override the location by
    setting the same variable to a path. Never overrides an explicit
    jax_compilation_cache_dir the user already configured."""
    import os

    loc = os.environ.get("DL4J_COMPILE_CACHE", "")
    if loc.lower() in ("off", "0", "none"):
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return
        path = loc or os.path.join(
            os.path.expanduser("~"), ".cache", "deeplearning4j_tpu",
            "xla_cache")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache anything that took meaningful compile time
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          0)
    except Exception:          # pragma: no cover - cache is best-effort
        pass


_wire_persistent_compile_cache()

from deeplearning4j_tpu.nn.config import (
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph

__version__ = "0.1.0"

__all__ = [
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
    "ComputationGraphConfiguration",
    "MultiLayerNetwork",
    "ComputationGraph",
    "__version__",
]
