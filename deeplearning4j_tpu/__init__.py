"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A brand-new framework with the capabilities of Deeplearning4j (reference:
codeinvento/deeplearning4j), designed TPU-first on JAX/XLA/Pallas:

- configuration-driven sequential (``MultiLayerNetwork``) and DAG
  (``ComputationGraph``) models compiled to single XLA executables,
- pure-functional layers differentiated with ``jax.grad`` (no hand-written
  backward passes — the reference pairs ``activate``/``backpropGradient`` by
  hand, e.g. deeplearning4j-nn/.../nn/api/Layer.java:88),
- optimizers as pure update transforms over parameter pytrees,
- SPMD parallelism over ``jax.sharding.Mesh`` axes (data/model/pipeline)
  instead of the reference's threaded ParallelWrapper + Spark/Aeron stack.

Public API intentionally mirrors DL4J naming so a DL4J user can find their
way around: ``NeuralNetConfiguration``, ``MultiLayerConfiguration``,
``ComputationGraphConfiguration``, ``MultiLayerNetwork``, ``ComputationGraph``,
``ParallelWrapper``, ``Evaluation``, ``EarlyStoppingConfiguration``, etc.
"""

from deeplearning4j_tpu.nn.config import (
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph

__version__ = "0.1.0"

__all__ = [
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
    "ComputationGraphConfiguration",
    "MultiLayerNetwork",
    "ComputationGraph",
    "__version__",
]
