"""Learning-rate (and generally hyperparameter) schedules.

TPU-native analog of ``org.nd4j.linalg.schedule.ISchedule`` and its
implementations, consumed by layer/updater configs in the reference
(deeplearning4j-nn configs take ``IUpdater`` with an optional schedule).
Each schedule is a serializable dataclass with ``value_at(iteration, epoch)``
returning a jnp scalar — pure, so it can live inside a jitted train step
(iteration is a traced int32, not Python state).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_serializable


class Schedule:
    def value_at(self, iteration, epoch=0):
        raise NotImplementedError


@register_serializable
@dataclasses.dataclass(frozen=True)
class FixedSchedule(Schedule):
    value: float

    def value_at(self, iteration, epoch=0):
        return jnp.asarray(self.value, jnp.float32)


@register_serializable
@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(Schedule):
    initial_value: float
    gamma: float

    def value_at(self, iteration, epoch=0):
        return self.initial_value * jnp.power(self.gamma, iteration.astype(jnp.float32)
                                              if hasattr(iteration, "astype") else float(iteration))


@register_serializable
@dataclasses.dataclass(frozen=True)
class InverseSchedule(Schedule):
    initial_value: float
    gamma: float
    power: float

    def value_at(self, iteration, epoch=0):
        it = jnp.asarray(iteration, jnp.float32)
        return self.initial_value / jnp.power(1.0 + self.gamma * it, self.power)


@register_serializable
@dataclasses.dataclass(frozen=True)
class PolySchedule(Schedule):
    initial_value: float
    power: float
    max_iter: int

    def value_at(self, iteration, epoch=0):
        it = jnp.asarray(iteration, jnp.float32)
        frac = jnp.clip(it / float(self.max_iter), 0.0, 1.0)
        return self.initial_value * jnp.power(1.0 - frac, self.power)


@register_serializable
@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(Schedule):
    initial_value: float
    gamma: float
    step_size: int

    def value_at(self, iteration, epoch=0):
        it = jnp.asarray(iteration, jnp.float32)
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (it - self.step_size)))


@register_serializable
@dataclasses.dataclass(frozen=True)
class StepSchedule(Schedule):
    initial_value: float
    decay_rate: float
    step_size: int

    def value_at(self, iteration, epoch=0):
        it = jnp.asarray(iteration, jnp.float32)
        return self.initial_value * jnp.power(self.decay_rate,
                                              jnp.floor(it / float(self.step_size)))


@register_serializable
@dataclasses.dataclass(frozen=True)
class WarmupCosineSchedule(Schedule):
    """Linear warmup then cosine decay — the modern default for large-batch
    TPU training (no direct reference analog; added for pod-scale runs)."""
    peak_value: float
    warmup_iters: int
    total_iters: int
    end_value: float = 0.0

    def value_at(self, iteration, epoch=0):
        it = jnp.asarray(iteration, jnp.float32)
        warm = self.peak_value * it / jnp.maximum(float(self.warmup_iters), 1.0)
        denom = jnp.maximum(float(self.total_iters - self.warmup_iters), 1.0)
        frac = jnp.clip((it - self.warmup_iters) / denom, 0.0, 1.0)
        cos = self.end_value + 0.5 * (self.peak_value - self.end_value) * (
            1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(it < self.warmup_iters, warm, cos)
