"""Training core: optimizer assembly + the jitted train step.

Analog of the reference's Solver/ConvexOptimizer stack
(deeplearning4j-nn/.../optimize/Solver.java:43,
solvers/StochasticGradientDescent.java:42, BaseOptimizer.java:54) redesigned
for XLA: the whole step — forward, backward, gradient transform, parameter
update — is ONE jitted pure function with donated buffers, so XLA plans
memory across the entire step (the reference needs workspaces + flattened
views to get the same effect; see SURVEY §7.1).

Per-layer updater overrides and frozen layers map to
``optax.multi_transform`` over top-level parameter keys — the analog of the
reference's UpdaterBlock grouping (nn/updater/UpdaterBlock.java:25).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.optimize.updaters import (
    GradientNormalizationConfig,
    NoOp,
    Updater,
)


class TrainState(NamedTuple):
    """Pytree carried across iterations. ``model_state`` holds non-trainable
    layer state (BN running stats, last RNN hidden states). ``telemetry``
    carries the on-device metrics ring buffer (observe/telemetry.py) when
    a collector is attached; the default is an empty pytree so untracked
    code constructing 4-field TrainStates keeps working."""
    params: Any
    model_state: Any
    opt_state: Any
    iteration: jnp.ndarray  # int32 scalar
    telemetry: Any = ()


def build_optimizer(
    layer_names: Tuple[str, ...],
    layer_updaters: Dict[str, Optional[Updater]],
    frozen: Dict[str, bool],
    global_updater: Updater,
    grad_norm: Optional[GradientNormalizationConfig] = None,
) -> optax.GradientTransformation:
    """Assemble the gradient transformation for a model.

    Layers with ``updater=None`` use the global updater; frozen layers get
    ``set_to_zero`` (reference: FrozenLayer wraps the layer with a NoOp
    updater — nn/conf/layers/misc/FrozenLayer.java).
    """
    groups: Dict[str, optax.GradientTransformation] = {
        "__global__": global_updater.to_optax()}
    labels: Dict[str, str] = {}
    for name in layer_names:
        if frozen.get(name, False):
            groups.setdefault("__frozen__", NoOp().to_optax())
            labels[name] = "__frozen__"
        elif layer_updaters.get(name) is not None:
            groups[name] = layer_updaters[name].to_optax()
            labels[name] = name
        else:
            labels[name] = "__global__"

    if len(set(labels.values())) == 1 and "__global__" in set(labels.values()):
        tx = groups["__global__"]
    else:
        tx = optax.multi_transform(groups, labels)

    clip = grad_norm.to_optax() if grad_norm is not None else None
    if clip is not None:
        tx = optax.chain(clip, tx)
    return tx


LossFn = Callable[..., Tuple[jnp.ndarray, Any]]


def make_train_step(loss_fn: LossFn, tx: optax.GradientTransformation,
                    donate: bool = True, constrain_fn=None,
                    telemetry=None):
    """Build the jitted train step.

    ``loss_fn(params, model_state, features, labels, fmask, lmask, rng,
    iteration) -> (loss, new_model_state)``

    Returns ``step(train_state, features, labels, fmask, lmask, rng) ->
    (new_train_state, loss)``. The train state is donated: XLA reuses the
    parameter/optimizer buffers in place, halving peak HBM — the analog of
    the reference's workspace reuse (WorkspaceMode; SURVEY §2.14).

    ``telemetry``: optional ``TelemetrySpec`` (observe/telemetry.py).
    When given, the step computes the spec's metrics from the in-flight
    loss/grads/updates and appends one row to the on-device ring buffer
    carried in ``TrainState.telemetry`` — no host interaction; the host
    fetches the ring in one transfer every N steps.
    """

    def step(ts: TrainState, features, labels, fmask, lmask, rng):
        def lf(params):
            return loss_fn(params, ts.model_state, features, labels, fmask,
                           lmask, rng, ts.iteration)

        (loss, new_ms), grads = jax.value_and_grad(lf, has_aux=True)(ts.params)
        updates, new_opt = tx.update(grads, ts.opt_state, ts.params)
        new_params = optax.apply_updates(ts.params, updates)
        if constrain_fn is not None:
            new_params = constrain_fn(new_params)
        buf = ts.telemetry
        if telemetry is not None:
            buf = telemetry.record(buf, loss=loss, grads=grads,
                                   params=new_params,
                                   prev_params=ts.params,
                                   iteration=ts.iteration)
        return TrainState(new_params, new_ms, new_opt, ts.iteration + 1,
                          buf), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_scan_train_step(loss_fn: LossFn, tx: optax.GradientTransformation,
                         donate: bool = True, constrain_fn=None,
                         shadow_cast=None, telemetry=None):
    """Multi-step variant of ``make_train_step``: one dispatch runs K
    optimizer steps via ``lax.scan`` over pre-staged batches.

    Why this exists: each host→device dispatch carries fixed overhead
    (buffer-handle marshalling; tens of ms through tunneled PJRT
    transports — measured in benchmarks/step_overhead.py), so per-step
    dispatch caps small-step throughput. Scanning K steps device-side
    amortizes it K× and lets XLA overlap the scan with host work — the
    TPU analog of the reference keeping its fit loop inside one native
    workspace iteration.

    This is the step behind ``fit(..., k_steps=K)``: the DeviceFeeder
    (datasets/feeder.py) stages K prefetched batches as one stacked
    (K, B, ...) device array (ragged tails padded to the bucket size
    with a zero labels mask, so the whole epoch keeps one compiled
    signature) and the fit loop dispatches them here. ``None`` masks
    scan through as empty pytrees — a mask must be None for ALL K
    batches or an array for all K, which the feeder's bucket
    normalization guarantees.

    ``shadow_cast``: optional ``params -> low-precision params`` (e.g.
    ``lambda p: cast_params(p, "bfloat16")``). When given, the scan
    carries a CAST SHADOW of the parameters next to the f32 masters:
    forward/backward consume the shadow (the model's internal
    ``cast_params`` becomes an identity on already-bf16 leaves), the
    optimizer updates the f32 masters, and the shadow is refreshed in
    the update's epilogue — where XLA fuses the cast with the parameter
    write instead of re-reading every f32 master at the top of the next
    step's loss (the ~6.8 ms/step recast measured on the BERT fine-tune
    config, PERF_ANALYSIS r5). Numerics are unchanged: the values the
    matmuls see are bit-identical either way.

    Returns ``steps(train_state, features, labels, fmask, lmask, rng) ->
    (new_train_state, per-step losses)`` where features/labels (and
    masks, if given) carry a leading K dim.
    """

    def one(carry, xs):
        ts, shadow = carry if shadow_cast is not None else (carry, None)
        work = shadow if shadow_cast is not None else ts.params
        features, labels, fmask, lmask, i = xs
        def lf(params):
            return loss_fn(params, ts.model_state, features, labels, fmask,
                           lmask, i[0], ts.iteration)
        (loss, new_ms), grads = jax.value_and_grad(lf, has_aux=True)(work)
        if shadow_cast is not None:
            # master-precision grads for the f32 optimizer state
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), grads, ts.params)
        updates, new_opt = tx.update(grads, ts.opt_state, ts.params)
        new_params = optax.apply_updates(ts.params, updates)
        if constrain_fn is not None:
            new_params = constrain_fn(new_params)
        buf = ts.telemetry
        if telemetry is not None:
            # identical row math to the unscanned step: per inner step,
            # from the same in-flight loss/grads/updates
            buf = telemetry.record(buf, loss=loss, grads=grads,
                                   params=new_params,
                                   prev_params=ts.params,
                                   iteration=ts.iteration)
        new_ts = TrainState(new_params, new_ms, new_opt,
                            ts.iteration + 1, buf)
        if shadow_cast is not None:
            return (new_ts, shadow_cast(new_params)), loss
        return new_ts, loss

    def steps(ts: TrainState, features, labels, fmask, lmask, rng):
        k = features[0].shape[0] if isinstance(features, tuple) \
            else features.shape[0]
        keys = jax.random.split(rng, k)[:, None]
        init = (ts, shadow_cast(ts.params)) if shadow_cast is not None \
            else ts
        out, losses = jax.lax.scan(one, init,
                                   (features, labels, fmask, lmask, keys))
        if shadow_cast is not None:
            out = out[0]
        return out, losses

    return jax.jit(steps, donate_argnums=(0,) if donate else ())


def make_eval_step(forward_fn):
    """Jitted inference step: forward_fn(params, model_state, x, mask)."""
    return jax.jit(forward_fn)


def make_constrain_fn(layers):
    """Post-update parameter projection from per-layer constraint configs
    (reference: conf/constraint/ applied in BaseMultiLayerUpdater.update
    after the updater step). Returns None when no layer has constraints."""
    constrained = {l.name: l.constraints for l in layers if l.constraints}
    if not constrained:
        return None

    def constrain(params):
        out = dict(params)
        for name, constraints in constrained.items():
            p = out.get(name)
            if not p:
                continue
            for c in constraints:
                p = c.apply(p)
            out[name] = p
        return out

    return constrain
