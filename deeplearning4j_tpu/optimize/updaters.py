"""Updaters (optimizers).

TPU-native analog of the ND4J updater family consumed by the reference's
layer configs (``org.nd4j.linalg.learning.config.IUpdater``: Sgd, Adam,
Nesterovs, RMSProp, AdaGrad, ...) and the updater engine that maps gradient
views to them (deeplearning4j-nn/.../nn/updater/BaseMultiLayerUpdater.java:38,
UpdaterBlock.java:25).

Design: the reference flattens all params into one buffer and runs updaters
over contiguous views so multi-layer updates are single native calls. On TPU
the equivalent is a pure optax ``GradientTransformation`` over the parameter
pytree inside one jitted train step — XLA fuses the whole update into a few
kernels, which is the same batching win without the view bookkeeping.

Per-layer updater overrides (DL4J allows a different updater per layer) are
supported via ``optax.multi_transform`` in the model builder.

Schedules: each updater takes either a float learning rate or a
:class:`~deeplearning4j_tpu.optimize.schedules.Schedule`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import optax

from deeplearning4j_tpu.optimize.schedules import Schedule
from deeplearning4j_tpu.utils.serde import register_serializable

LR = Union[float, Schedule]


def _lr_fn(lr: LR):
    if isinstance(lr, Schedule):
        return lambda count: lr.value_at(count)
    return lr


class Updater:
    """Base class for serializable updater configs."""

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    @property
    def has_state(self) -> bool:
        return True


@register_serializable
@dataclasses.dataclass(frozen=True)
class Sgd(Updater):
    learning_rate: LR = 1e-3

    def to_optax(self):
        return optax.sgd(_lr_fn(self.learning_rate))

    @property
    def has_state(self) -> bool:
        return False


@register_serializable
@dataclasses.dataclass(frozen=True)
class Nesterovs(Updater):
    learning_rate: LR = 0.1
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(_lr_fn(self.learning_rate), momentum=self.momentum,
                         nesterov=True)


@register_serializable
@dataclasses.dataclass(frozen=True)
class Adam(Updater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(_lr_fn(self.learning_rate), b1=self.beta1,
                          b2=self.beta2, eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class AdamW(Updater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 1e-2

    def to_optax(self):
        return optax.adamw(_lr_fn(self.learning_rate), b1=self.beta1,
                           b2=self.beta2, eps=self.epsilon,
                           weight_decay=self.weight_decay)


@register_serializable
@dataclasses.dataclass(frozen=True)
class AdaMax(Updater):
    learning_rate: LR = 2e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adamax(_lr_fn(self.learning_rate), b1=self.beta1,
                            b2=self.beta2, eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class Nadam(Updater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.nadam(_lr_fn(self.learning_rate), b1=self.beta1,
                           b2=self.beta2, eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class AMSGrad(Updater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.amsgrad(_lr_fn(self.learning_rate), b1=self.beta1,
                             b2=self.beta2, eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class RmsProp(Updater):
    learning_rate: LR = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(_lr_fn(self.learning_rate), decay=self.rms_decay,
                             eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class AdaGrad(Updater):
    learning_rate: LR = 1e-1
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(_lr_fn(self.learning_rate), eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adadelta(rho=self.rho, eps=self.epsilon)


@register_serializable
@dataclasses.dataclass(frozen=True)
class NoOp(Updater):
    """Frozen parameters — the reference uses NoOp for FrozenLayer."""

    def to_optax(self):
        return optax.set_to_zero()

    @property
    def has_state(self) -> bool:
        return False


@register_serializable
@dataclasses.dataclass(frozen=True)
class GradientNormalizationConfig:
    """Gradient normalization/clipping, analog of the reference's
    ``GradientNormalization`` enum (deeplearning4j-nn/.../nn/conf/
    GradientNormalization.java): renormalize by layer-wise L2, clip
    elementwise, clip by global L2 norm."""
    kind: str = "none"  # none|renormalize_l2|clip_value|clip_l2_per_layer|clip_l2_global
    threshold: float = 1.0

    def to_optax(self) -> Optional[optax.GradientTransformation]:
        if self.kind == "none":
            return None
        if self.kind == "clip_value":
            return optax.clip(self.threshold)
        if self.kind == "clip_l2_global":
            return optax.clip_by_global_norm(self.threshold)
        if self.kind in ("renormalize_l2", "clip_l2_per_layer"):
            import jax
            import jax.numpy as jnp

            def update_fn(updates, state, params=None):
                def per_leaf(g):
                    n = jnp.linalg.norm(g.reshape(-1))
                    if self.kind == "renormalize_l2":
                        return g / jnp.maximum(n, 1e-8)
                    scale = jnp.minimum(1.0, self.threshold / jnp.maximum(n, 1e-8))
                    return g * scale
                return jax.tree_util.tree_map(per_leaf, updates), state

            return optax.GradientTransformation(lambda params: optax.EmptyState(),
                                                update_fn)
        raise ValueError(f"unknown gradient normalization kind: {self.kind}")
