"""Legacy full-batch convex optimizers: conjugate gradient, L-BFGS,
backtracking line search.

Analogs of the reference's ``optimize/solvers/ConjugateGradient.java``,
``LBFGS.java`` and ``BackTrackLineSearch.java`` (SURVEY §2.1
"Optimizer/solver" — the non-SGD OptimizationAlgorithm values). The
reference drives these over the flattened parameter view; here the pytree
is raveled with ``jax.flatten_util.ravel_pytree`` and the loss/gradient
evaluation is one jitted function, so each line-search probe is a single
XLA execution.

These are host-driven loops (classic numeric optimizers with
data-dependent termination), which is fine: each iteration's device work
is a fused value_and_grad call; the Python loop only sequences them.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


class BackTrackLineSearch:
    """Armijo backtracking line search (reference:
    BackTrackLineSearch.java — maxIterations, stepMax, relTolx defaults)."""

    def __init__(self, max_iterations: int = 5, step_max: float = 100.0,
                 c1: float = 1e-4, backtrack: float = 0.5):
        self.max_iterations = max_iterations
        self.step_max = step_max
        self.c1 = c1
        self.backtrack = backtrack

    def search(self, f: Callable[[jnp.ndarray], jnp.ndarray],
               x: jnp.ndarray, loss0: float, grad: jnp.ndarray,
               direction: jnp.ndarray
               ) -> Tuple[float, float, jnp.ndarray]:
        """Returns (step, new_loss, direction_used); step==0.0 when no
        decrease found. ``direction_used`` is the (possibly flipped)
        direction actually probed — callers must step along it."""
        dnorm = float(jnp.linalg.norm(direction))
        if dnorm == 0.0 or not np.isfinite(dnorm):
            return 0.0, loss0, direction
        step = min(1.0, self.step_max / dnorm)
        slope = float(jnp.vdot(grad, direction))
        if slope >= 0:  # not a descent direction: flip
            direction = -direction
            slope = -slope
        for _ in range(self.max_iterations):
            new_loss = float(f(x + step * direction))
            if np.isfinite(new_loss) and \
                    new_loss <= loss0 + self.c1 * step * slope:
                return step, new_loss, direction
            step *= self.backtrack
        return 0.0, loss0, direction


class _Result(NamedTuple):
    params: object
    loss: float
    iterations: int
    converged: bool


class BaseLegacyOptimizer:
    """Shared driver (reference: BaseOptimizer.java:54 — maxIterations +
    score-delta termination)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5,
                 line_search: Optional[BackTrackLineSearch] = None):
        self.max_iterations = max_iterations
        self.tolerance = tolerance
        self.line_search = line_search or BackTrackLineSearch()

    def optimize(self, loss_fn: Callable, params) -> _Result:
        """loss_fn: pytree -> scalar. Returns optimized pytree."""
        x0, unravel = ravel_pytree(params)

        @jax.jit
        def f(x):
            return loss_fn(unravel(x))

        vg = jax.jit(jax.value_and_grad(f))
        x, loss, it, conv = self._run(f, vg, x0)
        return _Result(unravel(x), float(loss), it, conv)

    def _run(self, f, vg, x):
        raise NotImplementedError


class ConjugateGradient(BaseLegacyOptimizer):
    """Polak-Ribiere nonlinear CG (reference: ConjugateGradient.java)."""

    def _run(self, f, vg, x):
        loss, g = vg(x)
        loss = float(loss)
        d = -g
        for it in range(self.max_iterations):
            step, new_loss, d = self.line_search.search(f, x, loss, g, d)
            if step == 0.0:  # line-search breakdown, not convergence
                return x, loss, it, False
            x = x + step * d
            _, g_new = vg(x)
            # Polak-Ribiere beta, clamped at 0 (auto-restart)
            beta = float(jnp.vdot(g_new, g_new - g) /
                         (jnp.vdot(g, g) + 1e-30))
            beta = max(0.0, beta)
            d = -g_new + beta * d
            g = g_new
            if abs(loss - new_loss) < self.tolerance:
                return x, new_loss, it + 1, True
            loss = new_loss
        return x, loss, self.max_iterations, False


class LBFGS(BaseLegacyOptimizer):
    """Limited-memory BFGS, two-loop recursion (reference: LBFGS.java —
    default history m=4)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5,
                 m: int = 4, line_search: Optional[BackTrackLineSearch] = None):
        super().__init__(max_iterations, tolerance, line_search)
        self.m = m

    def _run(self, f, vg, x):
        loss, g = vg(x)
        loss = float(loss)
        s_hist, y_hist = [], []
        for it in range(self.max_iterations):
            d = -self._two_loop(g, s_hist, y_hist)
            step, new_loss, d = self.line_search.search(f, x, loss, g, d)
            if step == 0.0:  # line-search breakdown, not convergence
                return x, loss, it, False
            x_new = x + step * d
            _, g_new = vg(x_new)
            s, y = x_new - x, g_new - g
            if float(jnp.vdot(s, y)) > 1e-10:
                s_hist.append(s)
                y_hist.append(y)
                if len(s_hist) > self.m:
                    s_hist.pop(0)
                    y_hist.pop(0)
            x, g = x_new, g_new
            if abs(loss - new_loss) < self.tolerance:
                return x, new_loss, it + 1, True
            loss = new_loss
        return x, loss, self.max_iterations, False

    @staticmethod
    def _two_loop(g, s_hist, y_hist):
        q = g
        alphas = []
        for s, y in zip(reversed(s_hist), reversed(y_hist)):
            rho = 1.0 / (jnp.vdot(y, s) + 1e-30)
            a = rho * jnp.vdot(s, q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        if s_hist:
            s, y = s_hist[-1], y_hist[-1]
            q = q * (jnp.vdot(s, y) / (jnp.vdot(y, y) + 1e-30))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + (a - b) * s
        return q


def optimize_model(model, dataset, algo: str = "lbfgs",
                   max_iterations: int = 100, tolerance: float = 1e-5
                   ) -> _Result:
    """Full-batch optimization of a model on one DataSet, the analog of
    configuring ``OptimizationAlgorithm.LBFGS``/``CONJUGATE_GRADIENT`` on
    the reference Solver (Solver.java:43). Updates model params in place."""
    import jax.random as jrandom

    algos = {"lbfgs": LBFGS, "cg": ConjugateGradient,
             "conjugate_gradient": ConjugateGradient}
    opt = algos[algo.lower()](max_iterations=max_iterations,
                              tolerance=tolerance)
    ts = model.train_state
    key = jrandom.PRNGKey(0)
    feats = jnp.asarray(dataset.features)
    labels = jnp.asarray(dataset.labels)
    # ComputationGraph takes tuples of inputs/labels; MLN takes arrays
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    graph = isinstance(model, ComputationGraph)
    f_in = (feats,) if graph else feats
    l_in = (labels,) if graph else labels

    def loss_fn(params):
        loss, _ = model._loss(params, ts.model_state, f_in, l_in,
                              None, None, key, ts.iteration)
        return loss

    res = opt.optimize(loss_fn, ts.params)
    model.set_params(res.params)
    return res
