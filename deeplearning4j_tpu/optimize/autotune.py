"""Measured autotuning: one persisted TunedConfig artifact per machine.

Every perf round so far hand-measured its sweet spots — serving
batch_limit, K steps/dispatch, generation slot geometry and prefill
chunk, retrieval nprobe, feeder depth — and PERF_ANALYSIS.md was the
only place those numbers lived. This module is the runtime half of the
autotune engine (the sweeps themselves live in
``benchmarks/autotune.py``): a registry of tunables with their
committed hand-tuned defaults, a :class:`TunedConfig` holding measured
winners, and a fingerprinted save/load path into the shared
:class:`~deeplearning4j_tpu.parallel.aot_cache.ArtifactStore` so one
tuning run on one node warms the whole fleet.

The persistence discipline mirrors the AOT executable cache exactly:

- the measured payload is a checksummed blob
  (``tuned_values.blob``), written through the same ``store.save``
  chaos seam the AOT blobs ride;
- the manifest (``tuned.json``) carries the fingerprint + the blob's
  sha256 and is written atomically LAST (tmp + ``os.replace``) — a
  reader mid-save just misses;
- the fingerprint is compared FIELD BY FIELD at load (backend
  platform/device kind, jax/jaxlib versions, tunable-registry version,
  optional model weights sha256). ANY mismatch falls through to the
  committed defaults — with a flight-recorder breadcrumb naming the
  diverged field — never a crash, never a CPU-container constant
  silently applied to a real chip;
- a blob failing its checksum (torn write, bit rot, armed chaos) is
  quarantined (``.quarantine`` rename) so later loads don't re-pay the
  failure, and the loader falls through to defaults.

Consumers resolve values through :func:`resolve_tuned` with a strict
precedence: an explicit constructor/CLI argument always wins, then the
engine's ``tuned_config=``, then the process-wide config installed by
:func:`set_process_tuned` (the ``serve --tuned-config`` path), then
the committed default. A consumer that never sees a tuned config
behaves bit-for-bit as before.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.parallel.aot_cache import (
    _first_mismatch, _mismatch_reason, weights_digest)

TUNED_FORMAT_VERSION = 1
# bump when a tunable's NAME or value SEMANTICS change: a config tuned
# against an older registry must fall through to defaults, not apply
# a value whose meaning drifted
TUNED_REGISTRY_VERSION = 1

TUNED_KEY = "tuned_config"          # default ArtifactStore key
TUNED_MANIFEST = "tuned.json"       # fingerprint + checksum, atomic-LAST
TUNED_BLOB = "tuned_values.blob"    # measured values + decisions


@dataclass(frozen=True)
class Tunable:
    """One registered knob: its committed hand-tuned default, the
    candidate grid a sweep measures, and how to read the score."""
    name: str
    default: Any
    candidates: Tuple[Any, ...]
    unit: str
    description: str
    higher_is_better: bool = True
    constraint: Optional[str] = None


REGISTRY: Dict[str, Tunable] = {t.name: t for t in (
    Tunable("serving.batch_limit", 32, (8, 16, 32, 64), "req/s",
            "ServingEngine max examples per dispatch; also the top "
            "rung of the pow2 bucket ladder the warmup sweep compiles "
            "(the ladder is derived, so tuning this tunes both)"),
    Tunable("fit.k_steps", 1, (1, 2, 4, 8), "steps/s",
            "optimizer steps fused into one device dispatch by the "
            "scanned train step (fit(k_steps=))"),
    Tunable("fit.batch", 256, (128, 256, 384), "examples/s",
            "training batch size the measured examples/s peaked at "
            "(advisory: the iterator owns the batch; readers query "
            "TunedConfig.get('fit.batch'))"),
    Tunable("feeder.depth", 2, (1, 2, 4), "steps/s",
            "DeviceFeeder prefetch depth: batches staged onto the "
            "device ahead of the step loop"),
    Tunable("generation.max_slots", 8, (2, 4, 8, 16), "tok/s",
            "continuous-batching slot count; the AOT warmup sweeps "
            "the pow2 slot ladder and the reachable resize pairs up "
            "to it, so tuning this also sizes the warm set"),
    Tunable("generation.prefill_chunk", 0, (0, 16, 64), "ms TTFT",
            "chunked-prefill scan width (pow2 chunk ladder below it "
            "is warmed); 0 = one-tick-per-token prefill",
            higher_is_better=False),
    Tunable("retrieval.nprobe", 64, (4, 8, 16, 32, 64), "qps",
            "IVF clusters probed per query; the recall@k floor is a "
            "CONSTRAINT on the sweep, not a tunable — a candidate "
            "below the floor can never win, whatever its qps",
            constraint="recall@10 >= 0.95 vs the exact f32 oracle"),
    Tunable("retrieval.k_ladder", (1, 10, 100), ((1, 10, 100), (10, 100)),
            "qps",
            "warmed k rungs; a request's k pads up to the next rung"),
    Tunable("ops.lstm_dispatch", (), ((),), "rules",
            "Pallas-LSTM fused-kernel crossover rules, tuples of "
            "(min_batch, min_hidden, min_seq); the fused path is "
            "taken when ANY rule matches. Empty = always the XLA "
            "scan. On a non-TPU backend the tuner records an explicit "
            "scan-fallback decision instead of leaving the table "
            "silently empty"),
)}


class TunedConfig:
    """Measured tunable values + the decision record behind each.

    ``values`` holds ONLY measured winners — :meth:`get` returns None
    for anything the sweep didn't cover, which is what lets the
    fall-through-to-defaults contract work per tunable rather than
    all-or-nothing. ``decisions`` keeps the full evidence per tunable
    (candidates, scores, exclusions, reason) for PERF_ANALYSIS tables
    and post-mortems. ``load_outcome``/``load_reason`` record how this
    config came to be (``measured``, ``loaded``, or one of the
    fall-through outcomes ``absent``/``mismatch``/``corrupt``)."""

    def __init__(self, values: Optional[Dict[str, Any]] = None,
                 decisions: Optional[Dict[str, Any]] = None,
                 fingerprint: Optional[Dict[str, Any]] = None,
                 source: str = "defaults"):
        self.values = dict(values or {})
        self.decisions = dict(decisions or {})
        self.fingerprint = fingerprint
        self.source = source
        self.load_outcome: Optional[str] = None
        self.load_reason: Optional[str] = None

    @classmethod
    def defaults(cls) -> "TunedConfig":
        """The committed hand-tuned defaults: an EMPTY value map, so
        every consumer resolves to its own constructor default — the
        exact pre-autotune behavior."""
        return cls(source="defaults")

    def get(self, name: str, default: Any = None) -> Any:
        """The measured value for ``name``, or ``default`` when the
        sweep didn't cover it (or this config is the fall-through)."""
        v = self.values.get(name)
        return default if v is None else v

    def effective(self, name: str) -> Any:
        """Measured value if present, else the committed default."""
        return self.get(name, REGISTRY[name].default)

    def record(self, decision: Dict[str, Any]) -> None:
        """Fold one sweep decision (from :func:`choose`) in."""
        name = decision["tunable"]
        if name not in REGISTRY:
            raise KeyError(f"unknown tunable {name!r}")
        self.values[name] = decision["value"]
        self.decisions[name] = decision

    def summary_rows(self) -> List[Tuple[str, Any, Any, str]]:
        """(name, tuned, default, reason) per decided tunable."""
        out = []
        for name in sorted(self.decisions):
            d = self.decisions[name]
            out.append((name, d.get("value"),
                        REGISTRY[name].default, d.get("reason", "")))
        return out


# ---- process-wide config (the `serve --tuned-config` path) --------------

_process_tuned: Optional[TunedConfig] = None
_process_lock = threading.Lock()


def set_process_tuned(cfg: Optional[TunedConfig]) -> None:
    """Install ``cfg`` as the process-wide tuned config every consumer
    falls back to when not handed one explicitly, and apply the
    process-global tunables that aren't constructor kwargs (the
    Pallas-LSTM dispatch table). ``None`` uninstalls."""
    global _process_tuned
    with _process_lock:
        _process_tuned = cfg
    from deeplearning4j_tpu.ops import pallas_lstm
    rules = cfg.get("ops.lstm_dispatch") if cfg is not None else None
    pallas_lstm.set_dispatch_rules(rules or None)


def process_tuned() -> Optional[TunedConfig]:
    with _process_lock:
        return _process_tuned


def tuned_value(name: str, tuned: Optional[TunedConfig] = None) -> Any:
    """The measured value for ``name`` from ``tuned`` (or the installed
    process config), or None when nothing tuned covers it. Use this
    where the committed default is contextual (e.g. retrieval nprobe
    falls back to the index build's own hint, not a registry scalar)."""
    cfg = tuned if tuned is not None else process_tuned()
    if cfg is None:
        return None
    return cfg.get(name)


def resolve_tuned(explicit: Any, tuned: Optional[TunedConfig],
                  name: str) -> Any:
    """Consumer-side precedence: explicit caller argument > measured
    tuned value (engine-local config, else the process config) >
    committed registry default."""
    if explicit is not None:
        return explicit
    v = tuned_value(name, tuned)
    if v is not None:
        return v
    return REGISTRY[name].default


# ---- sweep-side decision helper -----------------------------------------

def choose(tunable: Tunable,
           measured: List[Tuple[Any, Any]],
           *, excluded: Optional[Dict[Any, str]] = None,
           note: str = "") -> Dict[str, Any]:
    """Pick the winner from ``measured`` [(candidate, score), ...].

    Best score wins in the tunable's direction; a tie prefers the
    committed default, then the earlier candidate (deterministic).
    ``excluded`` maps candidates that can NEVER win to the reason
    (e.g. a recall-floor miss) — the constraint-not-a-tunable rule.
    Returns the decision record :meth:`TunedConfig.record` consumes.
    """
    excluded = excluded or {}

    def _key(cand):
        return json.dumps(cand, sort_keys=True)

    banned = {_key(c) for c in excluded}
    eligible = [(c, s) for c, s in measured if _key(c) not in banned]
    if not eligible:
        # every candidate violated the constraint: keep the committed
        # default — a sweep can refuse to decide, never force a bad value
        best, best_score = tunable.default, None
        reason = "no candidate met the constraint; kept default"
    else:
        sign = 1.0 if tunable.higher_is_better else -1.0
        best, best_score = eligible[0]
        for cand, score in eligible[1:]:
            if sign * score > sign * best_score or (
                    score == best_score and cand == tunable.default
                    and best != tunable.default):
                best, best_score = cand, score
        reason = note or (f"best measured {tunable.unit} across "
                          f"{len(measured)} cells")
    return {
        "tunable": tunable.name,
        "value": best,
        "default": tunable.default,
        "unit": tunable.unit,
        "higher_is_better": tunable.higher_is_better,
        "score": best_score,
        "measured": [[c, s] for c, s in measured],
        "excluded": [[c, why] for c, why in excluded.items()],
        "reason": reason,
    }


# ---- fingerprint ---------------------------------------------------------

def fingerprint(params: Any = None, *,
                model_version: Optional[str] = None) -> Dict[str, Any]:
    """Everything a tuned value's validity depends on, mirroring the
    AOT manifest's shape: the backend the sweep ran on (a CPU
    container's constants must never reach a real chip), the jax/jaxlib
    pair (dispatch overheads shift across releases), the tunable
    registry version, and — when the sweep was model-bound — the model
    weights sha256. ``params=None`` produces a machine-level
    fingerprint whose weights field is a wildcard at load."""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    return {
        "format_version": TUNED_FORMAT_VERSION,
        "registry_version": TUNED_REGISTRY_VERSION,
        "model_version": model_version,
        "weights_sha256": (weights_digest(params)
                           if params is not None else None),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": {"platform": dev.platform,
                    "device_kind": dev.device_kind},
    }


def _want_fields(expect: Dict[str, Any]) -> Dict[str, Any]:
    """The fields a loader actually pins: ``None``-valued optional
    bindings (weights_sha256, model_version) are wildcards — a
    machine-level consumer accepts any model's tuned artifact, but a
    model-bound expectation still rejects a foreign one. ``expect=None``
    pins nothing (every field a wildcard)."""
    want = dict(expect or {})
    for optional in ("weights_sha256", "model_version"):
        if want.get(optional) is None:
            want.pop(optional, None)
    return want


# ---- persistence ---------------------------------------------------------

def _loads_counter(registry):
    if registry is None:
        from deeplearning4j_tpu.observe.registry import default_registry
        registry = default_registry()
    return registry.counter(
        "dl4j_autotune_artifact_loads_total",
        "TunedConfig artifact load attempts; outcome=loaded (applied) "
        "| absent (no artifact yet) | mismatch (fingerprint field "
        "diverged -> committed defaults) | corrupt (checksum/parse "
        "failure -> blob quarantined, committed defaults)")


def save_tuned(store, cfg: TunedConfig, *, key: str = TUNED_KEY) -> str:
    """Publish ``cfg`` into the shared ArtifactStore under ``key``.

    Blob first (checksummed, riding the ``store.save`` chaos seam like
    the AOT blobs), manifest atomically LAST — a crash or a concurrent
    reader mid-save sees either the previous artifact or a clean miss,
    never a half-written config. Returns the object dir."""
    if cfg.fingerprint is None:
        raise ValueError("save_tuned needs cfg.fingerprint (use "
                         "autotune.fingerprint())")
    d = Path(store.cache_dir(key))
    payload = json.dumps({"values": cfg.values,
                          "decisions": cfg.decisions},
                         indent=2, sort_keys=True).encode("utf-8")
    checksum = hashlib.sha256(payload).hexdigest()
    chaos = chaos_site("store.save")
    blob = payload
    if chaos is not None:
        blob, _ = chaos.mangle(blob, arg="blob")
    (d / TUNED_BLOB).write_bytes(blob)  # graftlint: disable=atomic-write: blob bytes are sha256-checksummed and only become visible through the manifest's atomic os.replace; a torn blob quarantines at load
    manifest = json.dumps({"format_version": TUNED_FORMAT_VERSION,
                           "fingerprint": cfg.fingerprint,
                           "sha256": checksum},
                          indent=2).encode("utf-8")
    if chaos is not None:
        manifest, _ = chaos.mangle(manifest, arg="manifest")
    tmp = d / (TUNED_MANIFEST + ".tmp")
    tmp.write_bytes(manifest)
    os.replace(tmp, d / TUNED_MANIFEST)
    return str(d)


def _quarantine(path: Path) -> None:
    try:
        os.replace(path, str(path) + ".quarantine")
    except OSError:
        pass


def load_tuned(store, *, expect: Dict[str, Any], key: str = TUNED_KEY,
               registry=None, recorder=None) -> TunedConfig:
    """Load the tuned artifact under ``key``, validating its
    fingerprint field-by-field against ``expect`` (``None`` pins
    nothing — any artifact's fingerprint is accepted).

    Never raises. On any failure the returned config is the committed
    defaults with ``load_outcome`` / ``load_reason`` set:

    - ``absent``   no manifest published yet
    - ``mismatch`` a fingerprint field diverged (the reason names it)
    - ``corrupt``  unreadable manifest or a blob failing its checksum;
      the bad file is quarantined (``.quarantine``) so the failure is
      paid once
    - ``loaded``   fingerprint matched; measured values apply

    Every outcome increments ``dl4j_autotune_artifact_loads_total``
    and — via ``recorder.note`` when a FlightRecorder is passed —
    leaves a breadcrumb that rides any future crash dump, so a node
    serving on fall-through defaults explains itself post-mortem."""
    counter = _loads_counter(registry)

    def _fall_through(outcome: str, reason: str) -> TunedConfig:
        cfg = TunedConfig.defaults()
        cfg.load_outcome = outcome
        cfg.load_reason = reason
        counter.inc(1.0, outcome=outcome)
        if recorder is not None:
            recorder.note("autotune.tuned_config",
                          {"outcome": outcome, "reason": reason,
                           "key": key})
        return cfg

    d = Path(store.cache_dir(key))
    mpath = d / TUNED_MANIFEST
    if not mpath.exists():
        return _fall_through("absent", f"no {TUNED_MANIFEST} under "
                             f"{key!r}")
    try:
        manifest = json.loads(mpath.read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        _quarantine(mpath)
        return _fall_through(
            "corrupt", f"unreadable manifest ({type(e).__name__}); "
            "quarantined")
    got_fp = manifest.get("fingerprint", {})
    diff = _first_mismatch(_want_fields(expect), got_fp)
    if diff is not None:
        return _fall_through(
            "mismatch", _mismatch_reason(expect, got_fp, diff))
    bpath = d / TUNED_BLOB
    try:
        raw = bpath.read_bytes()
    except OSError as e:
        return _fall_through(
            "corrupt", f"blob unreadable ({type(e).__name__})")
    want_sha = manifest.get("sha256")
    if want_sha is None \
            or hashlib.sha256(raw).hexdigest() != want_sha:
        _quarantine(bpath)
        return _fall_through(
            "corrupt", "blob checksum mismatch; quarantined")
    try:
        payload = json.loads(raw.decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        _quarantine(bpath)
        return _fall_through(
            "corrupt", f"blob unparseable ({type(e).__name__}); "
            "quarantined")
    values = {k: v for k, v in (payload.get("values") or {}).items()
              if k in REGISTRY}
    cfg = TunedConfig(values, payload.get("decisions") or {},
                      fingerprint=got_fp, source=str(d))
    cfg.load_outcome = "loaded"
    counter.inc(1.0, outcome="loaded")
    if recorder is not None:
        recorder.note("autotune.tuned_config",
                      {"outcome": "loaded", "key": key,
                       "tunables": sorted(values)})
    return cfg
