"""Training listeners.

Analogs of the reference's listener SPI (deeplearning4j-nn/.../optimize/api/
TrainingListener.java) and stock impls (optimize/listeners/):
ScoreIterationListener, PerformanceListener (samples/sec, batches/sec, ETL ms
— PerformanceListener.java:99-112), CollectScoresIterationListener,
TimeIterationListener, EvaluativeListener, CheckpointListener
(listeners/checkpoint/CheckpointListener.java:72).

Listeners run on host, outside the jitted step; reading the loss forces a
device sync, so score-reporting listeners honor a ``frequency`` to avoid
stalling the TPU pipeline every iteration.
"""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

log = logging.getLogger(__name__)


class TrainingListener:
    def on_epoch_start(self, model, epoch: int):
        pass

    def on_epoch_end(self, model, epoch: int):
        pass

    def iteration_done(self, model, iteration: int, epoch: int,
                       loss, etl_ms: float, batch_size: int):
        pass


class ScoreIterationListener(TrainingListener):
    """Logs the loss every N iterations (reference: ScoreIterationListener)."""

    def __init__(self, frequency: int = 10):
        self.frequency = max(1, frequency)
        self.scores: List[float] = []

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if iteration % self.frequency == 0:
            score = float(loss)  # device sync
            self.scores.append(score)
            log.info("Score at iteration %d is %.6f", iteration, score)


class PerformanceListener(TrainingListener):
    """Throughput reporting: samples/sec, batches/sec, ETL ms — the metric
    definitions come from the reference (PerformanceListener.java:99-112)
    and feed BENCH results."""

    def __init__(self, frequency: int = 1, report_score: bool = False):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._samples = 0
        self.history: List[dict] = []

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        self._samples += batch_size
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0
            return
        if iteration % self.frequency == 0 and iteration > self._last_iter:
            dt = now - self._last_time
            batches = iteration - self._last_iter
            rec = {
                "iteration": iteration,
                "samples_per_sec": self._samples / dt,
                "batches_per_sec": batches / dt,
                "etl_ms": etl_ms,
            }
            if self.report_score:
                rec["score"] = float(loss)
            self.history.append(rec)
            log.info("iter %d: %.1f samples/sec, %.2f batches/sec, ETL %.2f ms",
                     iteration, rec["samples_per_sec"], rec["batches_per_sec"],
                     etl_ms)
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0


class CollectScoresIterationListener(TrainingListener):
    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(loss)))


class TimeIterationListener(TrainingListener):
    """ETA logging (reference: TimeIterationListener)."""

    def __init__(self, total_iterations: int, frequency: int = 10):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self._start = None

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = iteration / elapsed
            remaining = (self.total - iteration) / max(rate, 1e-9)
            log.info("iteration %d/%d, ETA %.1fs", iteration, self.total,
                     remaining)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (reference:
    EvaluativeListener)."""

    def __init__(self, iterator, frequency_epochs: int = 1):
        self.iterator = iterator
        self.frequency = max(1, frequency_epochs)
        self.evaluations: List = []

    def on_epoch_end(self, model, epoch):
        if epoch % self.frequency == 0:
            e = model.evaluate(self.iterator)
            self.evaluations.append((epoch, e))
            log.info("epoch %d eval: accuracy=%.4f", epoch, e.accuracy())


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with retention (reference: CheckpointListener
    — every N epochs/iterations, keepLast semantics)."""

    def __init__(self, directory: str, every_n_epochs: Optional[int] = None,
                 every_n_iterations: Optional[int] = None, keep_last: int = 3):
        self.dir = directory
        self.every_n_epochs = every_n_epochs
        self.every_n_iterations = every_n_iterations
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str):
        from deeplearning4j_tpu.models.serialization import save_model
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        save_model(model, path, save_updater=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if (self.every_n_iterations and iteration > 0
                and iteration % self.every_n_iterations == 0):
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model, epoch):
        if self.every_n_epochs and (epoch + 1) % self.every_n_epochs == 0:
            self._save(model, f"epoch_{epoch}")
