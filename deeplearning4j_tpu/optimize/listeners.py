"""Training listeners.

Analogs of the reference's listener SPI (deeplearning4j-nn/.../optimize/api/
TrainingListener.java) and stock impls (optimize/listeners/):
ScoreIterationListener, PerformanceListener (samples/sec, batches/sec, ETL ms
— PerformanceListener.java:99-112), CollectScoresIterationListener,
TimeIterationListener, EvaluativeListener, CheckpointListener
(listeners/checkpoint/CheckpointListener.java:72).

Listeners run on host, outside the jitted step; reading the loss forces a
device sync, so score-reporting listeners honor a ``frequency`` to avoid
stalling the TPU pipeline every iteration.

When the model carries an ``observe.TelemetryCollector``
(``model.set_telemetry``), score-reporting listeners consume the
host-side values the collector flushed from the on-device ring buffer —
zero extra syncs, values lagging at most one flush interval — and only
fall back to ``float(loss)`` (a sync) on unmonitored models.
"""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

import numpy as np

log = logging.getLogger(__name__)


def _telemetry_score(model, loss):
    """(score, available): flushed loss when a collector is attached
    (never syncs; None until the first flush), else ``float(loss)`` —
    the legacy device sync, kept for unmonitored models."""
    tel = getattr(model, "telemetry", None)
    if tel is not None:
        return tel.last("loss"), tel.last_record() is not None
    return float(loss), True  # host-sync-ok: unmonitored fallback


class TrainingListener:
    def on_epoch_start(self, model, epoch: int):
        pass

    def on_epoch_end(self, model, epoch: int):
        pass

    def iteration_done(self, model, iteration: int, epoch: int,
                       loss, etl_ms: float, batch_size: int):
        pass

    def on_crash_dump(self, model, path: str, reason: str):
        """Fired by the flight recorder (observe/flight_recorder.py) right
        after a post-mortem dump directory is written — ``reason`` is one
        of ``nonfinite`` / ``oom`` / ``exception``. Default: no-op."""
        pass


class ScoreIterationListener(TrainingListener):
    """Logs the loss every N iterations (reference: ScoreIterationListener)."""

    def __init__(self, frequency: int = 10):
        self.frequency = max(1, frequency)
        self.scores: List[float] = []

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if iteration % self.frequency == 0:
            score, ok = _telemetry_score(model, loss)
            if not ok:
                return  # monitored model, nothing flushed yet: no sync
            self.scores.append(score)
            log.info("Score at iteration %d is %.6f", iteration, score)


class PerformanceListener(TrainingListener):
    """Throughput reporting: samples/sec, batches/sec, ETL ms — the metric
    definitions come from the reference (PerformanceListener.java:99-112)
    and feed BENCH results."""

    def __init__(self, frequency: int = 1, report_score: bool = False):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self._last_time: Optional[float] = None
        self._last_iter: Optional[int] = None
        self._samples = 0
        # ETL accumulates over the whole reporting window: reporting only
        # the last iteration's ETL hid stalls on the skipped iterations
        self._etl_sum = 0.0
        self._etl_n = 0
        self.history: List[dict] = []

    def on_epoch_start(self, model, epoch: int):
        # seed the clock BEFORE the first batch runs, so its samples and
        # wall time both count (previously the first batch only set the
        # baseline and its samples were silently dropped)
        if self._last_time is None:
            self._last_time = time.perf_counter()

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        self._samples += batch_size
        self._etl_sum += float(etl_ms)
        self._etl_n += 1
        now = time.perf_counter()
        if self._last_iter is None:
            # attribute exactly this one batch to the window; without an
            # on_epoch_start seed (direct calls) fall back to `now` —
            # that window is empty and reports on the next iteration
            self._last_iter = iteration - 1
            if self._last_time is None:
                self._last_time = now
                self._samples = 0
                self._etl_sum = 0.0
                self._etl_n = 0
        if iteration % self.frequency == 0 and iteration > self._last_iter:
            dt = now - self._last_time
            if dt <= 0:
                return
            batches = iteration - self._last_iter
            rec = {
                "iteration": iteration,
                "samples_per_sec": self._samples / dt,
                "batches_per_sec": batches / dt,
                # mean over the window, not the last iteration's value
                "etl_ms": self._etl_sum / max(1, self._etl_n),
            }
            if self.report_score:
                score, ok = _telemetry_score(model, loss)
                if ok:
                    rec["score"] = score
            self.history.append(rec)
            log.info("iter %d: %.1f samples/sec, %.2f batches/sec, ETL %.2f ms",
                     iteration, rec["samples_per_sec"], rec["batches_per_sec"],
                     rec["etl_ms"])
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0
            self._etl_sum = 0.0
            self._etl_n = 0


class CollectScoresIterationListener(TrainingListener):
    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if iteration % self.frequency == 0:
            score, ok = _telemetry_score(model, loss)
            if ok:
                self.scores.append((iteration, score))


class TimeIterationListener(TrainingListener):
    """ETA logging (reference: TimeIterationListener)."""

    def __init__(self, total_iterations: int, frequency: int = 10):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self._start = None

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = iteration / elapsed
            remaining = (self.total - iteration) / max(rate, 1e-9)
            log.info("iteration %d/%d, ETA %.1fs", iteration, self.total,
                     remaining)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (reference:
    EvaluativeListener)."""

    def __init__(self, iterator, frequency_epochs: int = 1):
        self.iterator = iterator
        self.frequency = max(1, frequency_epochs)
        self.evaluations: List = []

    def on_epoch_end(self, model, epoch):
        if epoch % self.frequency == 0:
            from deeplearning4j_tpu.observe.tracer import get_tracer
            with get_tracer(model).span("eval", cat="eval", epoch=epoch):
                e = model.evaluate(self.iterator)
            self.evaluations.append((epoch, e))
            log.info("epoch %d eval: accuracy=%.4f", epoch, e.accuracy())


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with retention (reference: CheckpointListener
    — every N epochs/iterations, keepLast semantics)."""

    def __init__(self, directory: str, every_n_epochs: Optional[int] = None,
                 every_n_iterations: Optional[int] = None, keep_last: int = 3):
        self.dir = directory
        self.every_n_epochs = every_n_epochs
        self.every_n_iterations = every_n_iterations
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str):
        from deeplearning4j_tpu.models.serialization import save_model
        from deeplearning4j_tpu.observe.tracer import get_tracer
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        with get_tracer(model).span("checkpoint", cat="io", tag=tag):
            save_model(model, path, save_updater=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    def iteration_done(self, model, iteration, epoch, loss, etl_ms, batch_size):
        if (self.every_n_iterations and iteration > 0
                and iteration % self.every_n_iterations == 0):
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model, epoch):
        if self.every_n_epochs and (epoch + 1) % self.every_n_epochs == 0:
            self._save(model, f"epoch_{epoch}")


class SleepyTrainingListener(TrainingListener):
    """Sleeps for a configured time at training phases — a throttle for
    debugging/profiling or resource-sharing runs (reference:
    optimize/listeners/SleepyTrainingListener.java).

    ``time_mode="additive"`` always sleeps the full timer;
    ``"connected"`` subtracts the elapsed wall time since the phase last
    fired, sleeping only up to the target period (the reference's
    TimeMode.CONNECTED). The reference's SleepMode (park vs busy-spin) is
    a JVM-scheduler concern with no Python analog — time.sleep is used.
    """

    def __init__(self, timer_epoch_start_ms: float = 0.0,
                 timer_epoch_end_ms: float = 0.0,
                 timer_iteration_ms: float = 0.0,
                 time_mode: str = "additive"):
        if time_mode not in ("additive", "connected"):
            raise ValueError(f"unknown time_mode: {time_mode}")
        self.timer_es = timer_epoch_start_ms
        self.timer_ee = timer_epoch_end_ms
        self.timer_it = timer_iteration_ms
        self.time_mode = time_mode
        self._last = {}

    def _sleep(self, phase: str, timer_ms: float):
        if timer_ms <= 0:
            return
        if self.time_mode == "connected":
            last = self._last.get(phase)
            if last is not None:
                timer_ms -= (time.perf_counter() - last) * 1000.0
        if timer_ms >= 1.0:
            time.sleep(timer_ms / 1000.0)
        # record AFTER sleeping: the next period starts when this phase
        # releases, else elapsed would include our own sleep and the
        # throttle would fire every other call at double rate
        self._last[phase] = time.perf_counter()

    def on_epoch_start(self, model, epoch):
        self._sleep("es", self.timer_es)

    def on_epoch_end(self, model, epoch):
        self._sleep("ee", self.timer_ee)

    def iteration_done(self, model, iteration, epoch, loss, etl_ms,
                       batch_size):
        self._sleep("it", self.timer_it)


class ParamAndGradientIterationListener(TrainingListener):
    """Text-format per-iteration parameter/update statistics — the UI
    histogram information for SSH-only sessions (reference:
    optimize/listeners/ParamAndGradientIterationListener.java: mean,
    min/max and mean-absolute-value of each parameter and gradient,
    tab-delimited to console and/or file).

    "Gradient" here is the applied update (param delta between
    iterations): the functional train step consumes raw gradients inside
    jit, so the observable quantity is the update — same convention as
    ui/stats.py's update statistics and strictly more informative for
    tuning (it includes the updater's transform).
    """

    def __init__(self, iterations: int = 1, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = True,
                 print_mean_abs_value: bool = True,
                 output_to_console: bool = True, file: str = None,
                 delimiter: str = "\t"):
        self.iterations = max(1, iterations)
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs = print_mean_abs_value
        self.output_to_console = output_to_console
        self.file = file
        self.delimiter = delimiter
        self._total = 0
        self._prev = None
        self._header_done = False
        if file:
            with open(file, "w"):
                pass

    @staticmethod
    def _flat(params):
        import jax
        out = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            name = ".".join(str(getattr(p, "key", p)) for p in path)
            out.append((name, np.asarray(leaf)))
        return out

    def _stats(self, arr):
        vals = []
        if self.print_mean:
            vals.append(float(arr.mean()))
        if self.print_min_max:
            vals.extend([float(arr.min()), float(arr.max())])
        if self.print_mean_abs:
            vals.append(float(np.abs(arr).mean()))
        return vals

    def _emit(self, line: str):
        if self.output_to_console:
            print(line)
        if self.file:
            try:
                with open(self.file, "a") as f:
                    f.write(line + "\n")
            except OSError:
                log.warning("ParamAndGradientIterationListener: write to "
                            "%s failed", self.file)

    def iteration_done(self, model, iteration, epoch, loss, etl_ms,
                       batch_size):
        self._total += 1
        report = self._total % self.iterations == 0
        # snapshot right before a reporting iteration, so the update
        # column is a single-step delta
        snapshot = (self.iterations > 1
                    and self._total % self.iterations
                    == self.iterations - 1)
        if not (report or snapshot):
            return          # no device→host param transfer on idle steps
        params = self._flat(model.train_state.params)
        if snapshot:
            self._prev = {n: a.copy() for n, a in params}
            return
        if self.print_header and not self._header_done:
            self._header_done = True
            cols = ["iteration", "score"]
            stat_names = ((["mean"] if self.print_mean else [])
                          + (["min", "max"] if self.print_min_max else [])
                          + (["meanAbs"] if self.print_mean_abs else []))
            for name, _ in params:
                cols += [f"param_{name}_{s}" for s in stat_names]
                cols += [f"update_{name}_{s}" for s in stat_names]
            self._emit(self.delimiter.join(cols))
        vals = [str(self._total), f"{float(loss):.6g}"]
        prev = self._prev or {}
        for name, arr in params:
            vals += [f"{v:.6g}" for v in self._stats(arr)]
            upd = arr - prev[name] if name in prev else np.zeros_like(arr)
            vals += [f"{v:.6g}" for v in self._stats(upd)]
        self._emit(self.delimiter.join(vals))
        self._prev = {n: a.copy() for n, a in params}
