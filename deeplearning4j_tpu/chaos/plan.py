"""Deterministic, seedable fault injection for the serving cluster.

The cluster's failure story used to be one hand-rolled SIGKILL in the
chaos soak. This module makes every rehearsed failure *injectable and
replayable*: a :class:`FaultPlan` holds a list of :class:`FaultSpec`
clauses, each targeting one named **site** (a seam the stack already
exposes — the remote dispatcher's transport call, the registry's
record write, the artifact store's blob write, the broker's frame ops,
the ui request handler) with one fault **kind**. Whether a given site
hit injects is decided by a counter-based splitmix64 draw — the same
PRNG discipline as ``nlp/pairgen.py`` — so a plan seed fully
determines the injection sequence: same seed ⇒ bitwise-identical
draws ⇒ identical faults, which is what lets the chaos-matrix test
assert *replay* rather than eyeball flakes.

Arming::

    DL4J_CHAOS="seed=42;remote.send:delay(p=0.25,ms=40);store.save:corrupt(count=1)"

or programmatically::

    from deeplearning4j_tpu import chaos
    chaos.arm("seed=7;registry.write:torn_write(count=1)")
    ...build the objects under test...   # sites bind at construction
    chaos.disarm()

Grammar: semicolon-separated clauses; ``seed=N`` sets the plan seed;
every other clause is ``site:kind`` or ``site:kind(k=v,...)`` with
params ``p`` (injection probability, default 1), ``count`` (max
injections for this spec), ``after`` (skip the first N site hits),
``ms`` (delay magnitude), ``skew_ms`` (clock-skew magnitude), ``arg``
(only inject when the caller's site argument — node id, topic, path —
equals this string).

Site vocabulary (what each instrumented seam understands):

    remote.send      delay | error | timeout          arg = node id
    remote.clock     clock_skew
    registry.write   torn_write | error               arg = node id
    store.save       torn_write | corrupt
    broker.publish   delay | error                    arg = topic
    broker.poll      delay | error                    arg = topic
    ui.request       delay | error | kill             arg = path
    serve.dispatch   delay | error
    neighbors.fanout error                            arg = node id

Every injection lands in ``plan.trace`` as ``(site, kind, hit, draw)``
and increments ``dl4j_chaos_injected_total{site,kind}``. Determinism
caveat: the per-site hit counter orders draws by *call order*, so
bitwise replay holds exactly when the driver is deterministic
(single-threaded matrix tests); under concurrent load the plan is
still seeded-random per hit, just not sequence-reproducible.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# splitmix64 — constants and mix identical to nlp/pairgen.py, so the
# chaos stream is the same bitwise-portable PRNG the trainers use
GOLDEN = 0x9E3779B97F4A7C15
M1 = 0xBF58476D1CE4E5B9
M2 = 0x94D049BB133111EB
_MASK = (1 << 64) - 1
_U53 = 1.0 / 9007199254740992.0          # 2**-53

KINDS = ("delay", "error", "timeout", "torn_write", "corrupt",
         "clock_skew", "kill")

KILL_EXIT_CODE = 137                      # SIGKILL's conventional rc


def _mix(z: int) -> int:
    z &= _MASK
    z ^= z >> 30
    z = (z * M1) & _MASK
    z ^= z >> 27
    z = (z * M2) & _MASK
    z ^= z >> 31
    return z


def site_seed(plan_seed: int, name: str) -> int:
    """Per-site stream seed: the plan seed folded with the site name,
    byte by byte, so every site draws from an independent stream."""
    z = _mix((plan_seed & _MASK) ^ 0x4348414F53000000)      # "CHAOS"
    for b in name.encode("utf-8"):
        z = _mix(z ^ ((b * M2) & _MASK))
    return z


class ChaosError(RuntimeError):
    """The injected failure — distinguishable from organic errors in
    logs, indistinguishable to the resilience machinery under test."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One clause of a plan: inject ``kind`` at ``site`` with
    probability ``p`` per hit, at most ``count`` times, skipping the
    first ``after`` hits, optionally filtered to one caller ``arg``."""
    site: str
    kind: str
    p: float = 1.0
    count: Optional[int] = None
    after: int = 0
    ms: float = 0.0
    skew_ms: float = 0.0
    arg: Optional[str] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"p={self.p} out of [0, 1]")


class Injection:
    """One landed fault: what the caller must act out. ``kind`` says
    how — sleep ``delay_s``, raise, mangle bytes via ``corrupted()``,
    or add ``skew_s`` to the clock."""

    __slots__ = ("site", "kind", "hit", "draw", "spec")

    def __init__(self, site: str, hit: int, draw: int, spec: FaultSpec):
        self.site = site
        self.kind = spec.kind
        self.hit = hit
        self.draw = draw
        self.spec = spec

    @property
    def delay_s(self) -> float:
        return self.spec.ms / 1e3

    @property
    def skew_s(self) -> float:
        return self.spec.skew_ms / 1e3

    def error(self) -> ChaosError:
        return ChaosError(
            f"chaos: injected {self.kind} at {self.site} "
            f"(hit {self.hit})")

    def corrupted(self, data: bytes) -> bytes:
        """Deterministically mangle a byte payload: ``torn_write``
        truncates (the torn half of an interrupted write), ``corrupt``
        flips one draw-addressed byte (bit rot)."""
        if self.kind == "torn_write":
            return data[: len(data) // 2]
        if self.kind == "corrupt":
            if not data:
                return data
            i = self.draw % len(data)
            return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        return data

    def __repr__(self):
        return (f"Injection({self.site}:{self.kind} hit={self.hit} "
                f"draw={self.draw:#x})")


class _Site:
    """The handle an instrumented seam holds. ``hit()`` is the
    primitive (one counter bump + at most one draw); ``fail``,
    ``mangle`` and ``skew`` wrap the common act-out patterns so call
    sites stay one line."""

    __slots__ = ("_plan", "name", "_specs", "_seed")

    def __init__(self, plan: "FaultPlan", name: str,
                 specs: List[FaultSpec]):
        self._plan = plan
        self.name = name
        self._specs = specs
        self._seed = site_seed(plan.seed, name)

    def hit(self, arg: Optional[str] = None) -> Optional[Injection]:
        plan = self._plan
        with plan._lock:
            k = plan._counters.get(self.name, 0)
            plan._counters[self.name] = k + 1
            draw = _mix(self._seed + ((k + 1) * GOLDEN & _MASK))
            for spec in self._specs:
                if spec.arg is not None and arg != spec.arg:
                    continue
                if k < spec.after:
                    continue
                fired = plan._fired.get(id(spec), 0)
                if spec.count is not None and fired >= spec.count:
                    continue
                if (draw >> 11) * _U53 >= spec.p:
                    continue
                plan._fired[id(spec)] = fired + 1
                inj = Injection(self.name, k, draw, spec)
                plan._record(inj)
                return inj
        return None

    def fail(self, arg: Optional[str] = None,
             raise_as=None) -> Optional[Injection]:
        """Act out the imperative kinds: sleep on ``delay``, raise on
        ``error``/``timeout``, exit on ``kill``. ``raise_as`` lets the
        seam pick the exception its retry machinery treats as organic
        (e.g. ConnectionError at the broker). Data kinds (torn_write/
        corrupt/clock_skew) are returned for the caller to interpret."""
        inj = self.hit(arg)
        if inj is None:
            return None
        if inj.kind == "delay":
            time.sleep(inj.delay_s)  # host-sync-ok: armed chaos only
        elif inj.kind == "error":
            if raise_as is not None:
                raise raise_as(f"chaos: injected error at {self.name} "
                               f"(hit {inj.hit})")
            raise inj.error()
        elif inj.kind == "timeout":
            cls = raise_as if raise_as is not None else TimeoutError
            raise cls(f"chaos: injected timeout at {self.name} "
                      f"(hit {inj.hit})")
        elif inj.kind == "kill":
            os._exit(KILL_EXIT_CODE)
        return inj

    def mangle(self, data: bytes, arg: Optional[str] = None
               ) -> Tuple[bytes, Optional[Injection]]:
        """Byte-payload sites: returns (possibly mangled data,
        injection). ``delay`` sleeps here too; ``error`` raises."""
        inj = self.hit(arg)
        if inj is None:
            return data, None
        if inj.kind == "delay":
            time.sleep(inj.delay_s)  # host-sync-ok: armed chaos only
            return data, inj
        if inj.kind == "error":
            raise inj.error()
        return inj.corrupted(data), inj

    def skew(self, arg: Optional[str] = None) -> float:
        """Clock sites: seconds of skew to add (0.0 when nothing
        fires)."""
        inj = self.hit(arg)
        if inj is not None and inj.kind == "clock_skew":
            return inj.skew_s
        return 0.0


class FaultPlan:
    """A seeded set of fault specs plus the per-site hit counters that
    make injection deterministic. Thread-safe; one plan is typically
    process-global (see :func:`arm`)."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0,
                 registry=None):
        self.specs = list(specs)
        self.seed = int(seed) & _MASK
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._fired: Dict[int, int] = {}
        #: every injection, in order: (site, kind, hit, draw) — the
        #: bitwise-replay evidence the matrix test compares
        self.trace: List[Tuple[str, str, int, int]] = []
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, []).append(s)
        if registry is None:
            from deeplearning4j_tpu.observe.registry import (
                default_registry)
            registry = default_registry()
        self._c_injected = registry.counter(
            "dl4j_chaos_injected_total",
            "faults injected by the armed FaultPlan, by site and kind")

    def site(self, name: str) -> Optional[_Site]:
        specs = self._by_site.get(name)
        if not specs:
            return None
        return _Site(self, name, specs)

    def _record(self, inj: Injection) -> None:
        # called under self._lock
        self.trace.append((inj.site, inj.kind, inj.hit, inj.draw))
        self._c_injected.inc(1.0, site=inj.site, kind=inj.kind)

    def injected(self) -> Dict[Tuple[str, str], int]:
        """Injection counts by (site, kind)."""
        out: Dict[Tuple[str, str], int] = {}
        with self._lock:
            for s, k, _, _ in self.trace:
                out[(s, k)] = out.get((s, k), 0) + 1
        return out

    def replay_signature(self) -> Tuple[Tuple[str, str, int, int], ...]:
        """Hashable injection-sequence fingerprint: two runs of the
        same seed over the same deterministic driver must compare
        equal."""
        with self._lock:
            return tuple(self.trace)

    def __repr__(self):
        return (f"FaultPlan(seed={self.seed}, specs={len(self.specs)}, "
                f"injected={len(self.trace)})")


def parse_plan(text: str, registry=None) -> FaultPlan:
    """Parse the ``DL4J_CHAOS`` grammar into a plan. Raises ValueError
    on malformed clauses — a misconfigured chaos run must fail loudly,
    not silently no-op."""
    seed = 0
    specs: List[FaultSpec] = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            seed = int(clause[5:], 0)
            continue
        head, _, paren = clause.partition("(")
        site_name, sep, kind = head.partition(":")
        if not sep or not site_name or not kind:
            raise ValueError(
                f"chaos clause {clause!r} is not site:kind(...)")
        params: Dict[str, object] = {}
        if paren:
            if not paren.endswith(")"):
                raise ValueError(f"unbalanced parens in {clause!r}")
            for kv in paren[:-1].split(","):
                kv = kv.strip()
                if not kv:
                    continue
                key, sep2, val = kv.partition("=")
                if not sep2:
                    raise ValueError(
                        f"chaos param {kv!r} is not k=v in {clause!r}")
                key = key.strip()
                val = val.strip()
                if key == "arg":
                    params[key] = val
                elif key in ("count", "after"):
                    params[key] = int(val, 0)
                elif key in ("p", "ms", "skew_ms"):
                    params[key] = float(val)
                else:
                    raise ValueError(
                        f"unknown chaos param {key!r} in {clause!r}")
        specs.append(FaultSpec(site=site_name.strip(),
                               kind=kind.strip(), **params))
    return FaultPlan(specs, seed=seed, registry=registry)


# ---------------------------------------------------------------------------
# process-global arming (what chaos.hook resolves against)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None
_ENV_CONSUMED = False
_ARM_LOCK = threading.Lock()


def arm(plan=None, registry=None) -> FaultPlan:
    """Activate a plan process-wide. ``plan`` may be a FaultPlan, a
    plan string, or None (parse ``DL4J_CHAOS`` from the environment).
    Arm BEFORE constructing the objects under test — sites bind at
    construction."""
    global _ACTIVE, _ENV_CONSUMED
    if plan is None:
        text = os.environ.get("DL4J_CHAOS")
        if text is None:
            raise ValueError("arm(): no plan given and DL4J_CHAOS "
                             "is not set")
        plan = text
    if isinstance(plan, str):
        plan = parse_plan(plan, registry=registry)
    with _ARM_LOCK:
        _ACTIVE = plan
        _ENV_CONSUMED = True
    return plan


def disarm() -> None:
    """Deactivate chaos: later site resolutions return None (already
    bound handles keep their plan — rebuild the object to unhook it).
    Also blocks re-arming from a still-set DL4J_CHAOS."""
    global _ACTIVE, _ENV_CONSUMED
    with _ARM_LOCK:
        _ACTIVE = None
        _ENV_CONSUMED = True


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def site(name: str) -> Optional[_Site]:
    """Resolve a site against the active plan, auto-arming from
    ``DL4J_CHAOS`` on first touch (what ``chaos.hook`` calls)."""
    global _ACTIVE, _ENV_CONSUMED
    if _ACTIVE is None:
        with _ARM_LOCK:
            if _ACTIVE is None and not _ENV_CONSUMED:
                text = os.environ.get("DL4J_CHAOS")
                _ENV_CONSUMED = True
                if text:
                    _ACTIVE = parse_plan(text)
    if _ACTIVE is None:
        return None
    return _ACTIVE.site(name)
