"""The one chaos import allowed in hot paths.

Instrumented modules bind their injection sites ONCE at construction::

    from deeplearning4j_tpu.chaos.hook import chaos_site
    ...
    self._chaos = chaos_site("remote.send")     # None when disarmed

and their hot loops pay a single ``if self._chaos is not None`` test.
``chaos_site`` itself never loads the plan machinery unless chaos is
armed — via ``DL4J_CHAOS`` in the environment, or programmatically
(``chaos.arm(...)``, which imports ``chaos.plan`` and so flips the
``sys.modules`` probe below). Disarmed processes therefore never pay
an import, a parse, or a per-call draw: the zero-overhead contract the
``chaos-hygiene`` graftlint rule polices.
"""

from __future__ import annotations

import os
import sys


def chaos_site(name: str):
    """Resolve a fault-injection site handle, or ``None`` when chaos
    is disarmed. Call at construction time, not per operation."""
    if ("DL4J_CHAOS" not in os.environ
            and "deeplearning4j_tpu.chaos.plan" not in sys.modules):
        return None
    from deeplearning4j_tpu.chaos import plan as _plan
    return _plan.site(name)
