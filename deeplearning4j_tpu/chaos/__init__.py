"""Deterministic fault injection (see ``chaos/plan.py``).

This package is import-light by design: ``chaos.hook.chaos_site`` is
the only symbol hot paths may touch (graftlint's ``chaos-hygiene``
rule enforces it), and everything else — FaultPlan, parse_plan,
arm/disarm — is re-exported lazily so ``import deeplearning4j_tpu.
chaos`` in a disarmed process never loads the plan machinery.
"""

from deeplearning4j_tpu.chaos.hook import chaos_site  # noqa: F401

_LAZY = ("FaultPlan", "FaultSpec", "Injection", "ChaosError",
         "parse_plan", "arm", "disarm", "active_plan", "site",
         "KILL_EXIT_CODE")

__all__ = ("chaos_site",) + _LAZY


def __getattr__(name):
    if name in _LAZY:
        from deeplearning4j_tpu.chaos import plan as _plan
        return getattr(_plan, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
