"""Int8 post-training quantization primitives.

The reference framework leans on ND4J's ``org.nd4j.linalg.compression``
codecs for smaller model artifacts; it has no inference-side integer
compute path. Here the serving stack gets a real one: per-channel
symmetric int8 weights + per-layer static activation scales, with the
hot matmul/conv running int8 x int8 -> int32 on the device
(``preferred_element_type=jnp.int32`` keeps XLA's integer MAC path —
on TPU this hits the MXU's int8 mode, on CPU the VNNI-style kernels)
and a fused dequant-rescale back to f32 for bias + activation.

Conventions (all symmetric, zero-point-free):

- **Weights** quantize per OUTPUT channel: scale[o] = absmax(W[..., o])
  / 127 so each channel uses the full int8 range regardless of the
  others. Dense kernels are (n_in, n_out) -> reduce axis 0; conv
  kernels are HWIO -> reduce axes (0, 1, 2).
- **Activations** quantize with ONE static scalar scale per layer,
  calibrated offline (parallel/quant.py) from observed ranges. Static
  (not dynamic) scales keep the executable free of data-dependent
  reductions on the request path.
- **Dequant** folds both scales into a single f32 multiply on the int32
  accumulator: y = (xq @ wq) * (x_scale * w_scale[o]).

Scale *computation* is host-side numpy (float32) so calibration is
bitwise deterministic across processes — the same sample stream must
produce the identical AOT-cache fingerprint (tests/test_aot_cache.py).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

Q_MAX = 127  # symmetric int8: [-127, 127]; -128 unused (keeps |q| symmetric)


# ---- host-side scale computation (numpy, deterministic) ------------------

def per_channel_scales(w: np.ndarray,
                       reduce_axes: Optional[Sequence[int]] = None
                       ) -> np.ndarray:
    """f32 scale per output channel (last axis): absmax / 127. Dead
    channels (all-zero) get scale 1.0 so dequant never divides by 0."""
    w = np.asarray(w, np.float32)  # host-sync-ok: quantization happens host-side once, before serving — numpy IS the point (bitwise-deterministic scales)
    if reduce_axes is None:
        reduce_axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=tuple(reduce_axes))
    amax = np.where(amax > 0, amax, np.float32(Q_MAX))
    return (amax / np.float32(Q_MAX)).astype(np.float32)


def quantize_weight(w: np.ndarray,
                    reduce_axes: Optional[Sequence[int]] = None
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-output-channel symmetric int8 quantization of a weight
    tensor whose LAST axis is the output channel. Returns
    ``(w_q int8, scales f32[n_out])``; ``w ≈ w_q * scales``."""
    w = np.asarray(w, np.float32)  # host-sync-ok: one-time host-side weight quantization, not a serving hot path
    scales = per_channel_scales(w, reduce_axes)
    q = np.rint(w / scales)                     # broadcast over last axis
    q = np.clip(q, -Q_MAX, Q_MAX).astype(np.int8)
    return q, scales


def quantize_rows(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-ROW symmetric int8 quantization of a matrix of row vectors
    (a retrieval corpus shard, a batch of session carries): scale[i] =
    absmax(x[i, :]) / 127, dead rows scale 1.0. Returns ``(x_q int8
    [N, D], scales f32 [N])``; ``x ≈ x_q * scales[:, None]``. The
    row-major twin of :func:`quantize_weight` (which reduces all-but-
    last); scales stay host numpy so two processes quantizing the same
    corpus produce bitwise-identical shards."""
    x = np.asarray(x, np.float32)  # host-sync-ok: one-time host-side corpus quantization at index build, not a query hot path
    amax = np.max(np.abs(x), axis=1)
    amax = np.where(amax > 0, amax, np.float32(Q_MAX))
    scales = (amax / np.float32(Q_MAX)).astype(np.float32)
    q = np.rint(x / scales[:, None])
    q = np.clip(q, -Q_MAX, Q_MAX).astype(np.int8)
    return q, scales


def activation_scale(amax: float) -> np.float32:
    """Static per-layer activation scale from a calibrated absmax."""
    a = np.float32(amax)
    if not np.isfinite(a) or a <= 0:
        a = np.float32(Q_MAX)                   # degenerate: identity scale
    return np.float32(a / np.float32(Q_MAX))


# ---- device-side quantized compute (jax, traced) -------------------------

def quantize_act(x: jnp.ndarray, x_scale) -> jnp.ndarray:
    """f32 activation -> int8 with the layer's static scale (symmetric,
    saturating). ``x_scale`` is a traced f32 scalar from the quantized
    params pytree — NOT a Python constant — so the exported StableHLO is
    parametric in it and one blob serves any calibration."""
    q = jnp.round(x.astype(jnp.float32) / x_scale)
    return jnp.clip(q, -Q_MAX, Q_MAX).astype(jnp.int8)


def int8_dot(x: jnp.ndarray, w_q: jnp.ndarray, w_scale: jnp.ndarray,
             x_scale: jnp.ndarray) -> jnp.ndarray:
    """``act(x) @ w_q`` in int8 with int32 accumulation and fused
    dequant-rescale: works on (N, F) and (N, T, F) alike (contracts the
    last axis of x with axis 0 of w_q, like the dense einsum)."""
    xq = quantize_act(x, x_scale)
    y32 = lax.dot_general(
        xq, w_q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return y32.astype(jnp.float32) * (x_scale * w_scale)


def int8_conv(x: jnp.ndarray, w_q: jnp.ndarray, w_scale: jnp.ndarray,
              x_scale: jnp.ndarray, *, window_strides, padding,
              rhs_dilation, dimension_numbers,
              feature_group_count: int = 1) -> jnp.ndarray:
    """Int8 convolution with int32 accumulation + fused dequant. The
    conv geometry kwargs are forwarded verbatim from the f32 layer so
    the quantized op computes the identical spatial map."""
    xq = quantize_act(x, x_scale)
    y32 = lax.conv_general_dilated(
        xq, w_q, window_strides=window_strides, padding=padding,
        rhs_dilation=rhs_dilation, dimension_numbers=dimension_numbers,
        feature_group_count=feature_group_count,
        preferred_element_type=jnp.int32)
    return y32.astype(jnp.float32) * (x_scale * w_scale)
