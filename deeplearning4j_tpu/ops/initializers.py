"""Weight initialization schemes.

TPU-native analog of the reference's ``WeightInit`` enum + ``WeightInitUtil``
(deeplearning4j-nn/.../nn/weights/WeightInit.java, WeightInitUtil.java).
Pure functions of a jax PRNG key — deterministic and reproducible across
hosts, which matters for SPMD: every host initializes identical replicated
params from the same key instead of broadcasting from a chief.
"""

from __future__ import annotations

import enum
from typing import Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_enum


@register_enum
class WeightInit(enum.Enum):
    ZERO = "zero"
    ONES = "ones"
    CONSTANT = "constant"
    NORMAL = "normal"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    RELU = "relu"            # He normal
    RELU_UNIFORM = "relu_uniform"
    HE_NORMAL = "he_normal"
    HE_UNIFORM = "he_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    VAR_SCALING_NORMAL_FAN_AVG = "vs_normal_fan_avg"
    IDENTITY = "identity"

    def init(self, key, shape: Sequence[int], fan_in: int, fan_out: int,
             dtype=jnp.float32, gain: float = 1.0) -> jnp.ndarray:
        return _init(self, key, tuple(shape), fan_in, fan_out, dtype, gain)


def _init(scheme, key, shape, fan_in, fan_out, dtype, gain):
    fi = max(int(fan_in), 1)
    fo = max(int(fan_out), 1)
    if scheme is WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme is WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if scheme is WeightInit.CONSTANT:
        return jnp.full(shape, gain, dtype)
    if scheme is WeightInit.NORMAL:
        return gain * jax.random.normal(key, shape, dtype) / jnp.sqrt(fi)
    if scheme is WeightInit.UNIFORM:
        a = gain / jnp.sqrt(fi)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme is WeightInit.XAVIER:
        std = gain * jnp.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)
    if scheme is WeightInit.XAVIER_UNIFORM:
        a = gain * jnp.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme is WeightInit.XAVIER_FAN_IN:
        return gain * jax.random.normal(key, shape, dtype) / jnp.sqrt(fi)
    if scheme is WeightInit.LECUN_NORMAL:
        return gain * jax.random.normal(key, shape, dtype) * jnp.sqrt(1.0 / fi)
    if scheme is WeightInit.LECUN_UNIFORM:
        a = gain * jnp.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme in (WeightInit.RELU, WeightInit.HE_NORMAL):
        return gain * jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fi)
    if scheme in (WeightInit.RELU_UNIFORM, WeightInit.HE_UNIFORM):
        a = gain * jnp.sqrt(6.0 / fi)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme is WeightInit.SIGMOID_UNIFORM:
        a = gain * 4.0 * jnp.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme is WeightInit.VAR_SCALING_NORMAL_FAN_AVG:
        std = gain * jnp.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)
    if scheme is WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2D shape")
        return gain * jnp.eye(shape[0], dtype=dtype)
    raise ValueError(f"unknown WeightInit: {scheme}")
