"""Loss functions.

TPU-native analog of ``org.nd4j.linalg.lossfunctions.LossFunctions`` that the
reference's output layers consume (deeplearning4j-nn/.../nn/conf/layers/
OutputLayer etc.). Every loss is a pure function
``loss(labels, preactivation_or_activation, mask) -> scalar`` — the gradient
w.r.t. the network comes from ``jax.grad`` through the whole model, so there
are no hand-written ``computeGradient`` twins.

All losses support optional per-example or per-timestep masks (the reference
threads masks through ``ILossFunction.computeScoreArray``; see SURVEY §5.7).
Score convention matches the reference: mean over (unmasked) examples.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_enum

_EPS = 1e-7


def _masked_mean(per_example: jnp.ndarray, mask) -> jnp.ndarray:
    """Mean over examples, honoring an optional {0,1} mask.

    ``per_example`` has shape (N,) or (N, T): loss already reduced over
    feature dims. Mask broadcasts against it.
    """
    if mask is None:
        return jnp.mean(per_example)
    mask = jnp.asarray(mask, per_example.dtype)
    mask = jnp.reshape(mask, per_example.shape)
    total = jnp.sum(per_example * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def _reduce_features(x: jnp.ndarray) -> jnp.ndarray:
    """Sum over the trailing feature axis, keeping (N,) or (N, T)."""
    return jnp.sum(x, axis=-1)


@register_enum
class LossFunction(enum.Enum):
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MAE = "mae"
    XENT = "xent"                      # binary cross-entropy (sigmoid out)
    MCXENT = "mcxent"                  # multi-class cross-entropy (softmax out)
    SPARSE_MCXENT = "sparse_mcxent"    # integer labels
    NEGATIVELOGLIKELIHOOD = "nll"
    KL_DIVERGENCE = "kld"
    COSINE_PROXIMITY = "cosine"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    POISSON = "poisson"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "msle"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mape"

    def __call__(self, labels, output, mask=None):
        return _FNS[self](labels, output, mask)


def mse(labels, output, mask=None):
    # Mean over features (reference: LossMSE = LossL2 / nOut).
    return _masked_mean(jnp.mean(jnp.square(output - labels), axis=-1), mask)


def l1(labels, output, mask=None):
    return _masked_mean(_reduce_features(jnp.abs(output - labels)), mask)


def l2(labels, output, mask=None):
    # L2 in the reference is the un-averaged-over-features squared error sum.
    return _masked_mean(_reduce_features(jnp.square(output - labels)), mask)


def mae(labels, output, mask=None):
    return _masked_mean(jnp.mean(jnp.abs(output - labels), axis=-1), mask)


def xent(labels, output, mask=None):
    p = jnp.clip(output, _EPS, 1.0 - _EPS)
    per = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
    return _masked_mean(_reduce_features(per), mask)


def mcxent(labels, output, mask=None):
    p = jnp.clip(output, _EPS, 1.0)
    return _masked_mean(-_reduce_features(labels * jnp.log(p)), mask)


def sparse_mcxent(labels, output, mask=None):
    labels = labels.astype(jnp.int32)
    p = jnp.clip(output, _EPS, 1.0)
    logp = jnp.log(p)
    per = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return _masked_mean(per, mask)


def kl_divergence(labels, output, mask=None):
    p = jnp.clip(output, _EPS, 1.0)
    t = jnp.clip(labels, _EPS, 1.0)
    return _masked_mean(_reduce_features(labels * (jnp.log(t) - jnp.log(p))), mask)


def cosine_proximity(labels, output, mask=None):
    ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + _EPS)
    on = output / (jnp.linalg.norm(output, axis=-1, keepdims=True) + _EPS)
    return _masked_mean(-_reduce_features(ln * on), mask)


def hinge(labels, output, mask=None):
    # labels in {-1, +1}
    return _masked_mean(_reduce_features(jnp.maximum(0.0, 1.0 - labels * output)), mask)


def squared_hinge(labels, output, mask=None):
    return _masked_mean(
        _reduce_features(jnp.square(jnp.maximum(0.0, 1.0 - labels * output))), mask
    )


def poisson(labels, output, mask=None):
    p = jnp.clip(output, _EPS, None)
    return _masked_mean(_reduce_features(p - labels * jnp.log(p)), mask)


def msle(labels, output, mask=None):
    per = jnp.square(jnp.log1p(jnp.maximum(output, 0)) - jnp.log1p(jnp.maximum(labels, 0)))
    return _masked_mean(_reduce_features(per), mask)


def mape(labels, output, mask=None):
    per = 100.0 * jnp.abs((labels - output) / jnp.clip(jnp.abs(labels), _EPS, None))
    return _masked_mean(jnp.mean(per, axis=-1), mask)


_FNS = {
    LossFunction.MSE: mse,
    LossFunction.L1: l1,
    LossFunction.L2: l2,
    LossFunction.MAE: mae,
    LossFunction.XENT: xent,
    LossFunction.MCXENT: mcxent,
    LossFunction.SPARSE_MCXENT: sparse_mcxent,
    LossFunction.NEGATIVELOGLIKELIHOOD: mcxent,  # same math as reference
    LossFunction.KL_DIVERGENCE: kl_divergence,
    LossFunction.COSINE_PROXIMITY: cosine_proximity,
    LossFunction.HINGE: hinge,
    LossFunction.SQUARED_HINGE: squared_hinge,
    LossFunction.POISSON: poisson,
    LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR: msle,
    LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR: mape,
}


def stable_mcxent_from_logits(labels, logits, mask=None):
    """Fused softmax+CE on logits — numerically stable path used by output
    layers when activation is SOFTMAX (avoids materializing the softmax;
    XLA fuses the log-sum-exp into the preceding matmul's epilogue)."""
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    per = _reduce_features(labels * (logz - logits))
    return _masked_mean(per, mask)


def stable_xent_from_logits(labels, logits, mask=None):
    """Fused sigmoid+BCE on logits."""
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _masked_mean(_reduce_features(per), mask)
