"""Fused Pallas LSTM recurrence — the cuDNN-LSTM-helper tier for TPU.

The reference accelerates LSTM with a dedicated cuDNN helper
(deeplearning4j-cuda/.../recurrent/CudnnLSTMHelper.java) because a
per-tick recurrence dominated by dispatch/HBM overhead is the classic
case where a hand-fused kernel beats the generic compiler path. Our XLA
scan has the same gap (PERF_ANALYSIS r5: ~23 µs/tick against a ~6 µs
matmul roofline at the BASELINE TextGenerationLSTM geometry, with Wh
(2.1 MB bf16) re-streamed from HBM every tick).

The kernel here runs the whole recurrence as ONE pallas_call:

- grid = (T/block_t,) with the time axis SEQUENTIAL ("arbitrary"), so
  Wh — whose BlockSpec index map is constant — is fetched into VMEM once
  and stays pinned across all ticks;
- the (h, c) carry lives in f32 VMEM scratch, never touching HBM
  between ticks;
- per tick the kernel reads one (N, 4H) slab of the pre-projected input
  zx (the x@Wx+b hoist stays outside, where the MXU runs it at full
  tilt over all timesteps at once) and writes the hidden output plus
  the activation residuals the backward pass needs;
- the backward is a second kernel walking the grid in REVERSE via its
  index maps, with (dh, dc) and the dWh accumulator in VMEM scratch —
  wrapped as a jax.custom_vjp so training uses it too.

Masking matches the scan cell exactly: masked ticks do not advance
(h, c); output zeroing stays in the layer.

Dispatch follows the helper-SPI-with-measured-crossover discipline of
``pallas_kernels.attention``: ``choose_impl`` routes to the fused
kernel only where ``benchmarks/lstm_crossover.py`` measurements say it
wins, falls back to the ``lax.scan`` cell otherwise, and any trace-time
kernel failure falls back silently (ConvolutionLayer.java:173
helperCountFail analog).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.pallas_kernels import _dim_sem

_IMPL_ENV = "DL4J_LSTM_IMPL"  # "fused" | "scan" | "auto" (default)

# Measured crossover thresholds from benchmarks/lstm_crossover.py runs on
# real hardware: rules of (min_batch, min_hidden, min_seq); the fused
# kernel is auto-selected when ANY rule is satisfied. EMPTY as of round 6:
# no TPU chip was attached to the builder session, so auto-dispatch stays
# on the scan path until the crossover bench is captured on hardware —
# thresholds here must come from measurements, not guesses (the attention
# crossover discipline). Opt in explicitly with DL4J_LSTM_IMPL=fused.
_MEASURED_FUSED_WINS: Tuple[Tuple[int, int, int], ...] = ()

# Runtime override installed by the autotune engine (the
# `ops.lstm_dispatch` tunable): a TunedConfig measured on THIS machine
# may carry crossover rules, and set_process_tuned() routes them here.
# None means "no tuned table installed — use the committed constant".
_runtime_rules: Optional[Tuple[Tuple[int, int, int], ...]] = None

_DEF_BLOCK_T = 1  # ticks per grid step; >1 amortizes per-step overhead
                  # at the price of VMEM (zx slab is N*4H*dtype per tick)


def dispatch_rules() -> Tuple[Tuple[int, int, int], ...]:
    """The crossover table in effect: the tuned runtime table when one
    was installed, else the committed measured constant."""
    return (_MEASURED_FUSED_WINS if _runtime_rules is None
            else _runtime_rules)


def set_dispatch_rules(rules) -> None:
    """Install (or with None, clear) a measured crossover table at
    runtime. Rules arrive from a persisted TunedConfig as lists of
    [min_batch, min_hidden, min_seq]; normalized to int tuples here."""
    global _runtime_rules
    if rules is None:
        _runtime_rules = None
        return
    _runtime_rules = tuple(
        (int(b), int(h), int(t)) for (b, h, t) in rules)


def fused_wins(batch: int, hidden: int, seq: int) -> bool:
    """True where the measured crossover table says the fused kernel
    beats the XLA scan on this (batch, hidden, seq) geometry."""
    return any(batch >= b and hidden >= h and seq >= t
               for (b, h, t) in dispatch_rules())


def choose_impl(batch: int, hidden: int, seq: int,
                backend: Optional[str] = None) -> str:
    """Dispatch decision: 'fused' or 'scan'."""
    mode = os.environ.get(_IMPL_ENV, "auto")
    if mode in ("fused", "scan"):
        return mode
    backend = backend or jax.default_backend()
    if backend == "tpu" and fused_wins(batch, hidden, seq):
        return "fused"
    return "scan"


def _fwd_kernel(zx_ref, h0_ref, c0_ref, wh_ref, mask_ref,
                ys_ref, gates_ref, tc_ref, cc_ref, hT_ref, cT_ref,
                h_scr, c_scr, *, block_t: int, hidden: int):
    """block_t ticks of the recurrence. Carry (h, c) persists in f32
    scratch across the sequential grid; Wh stays resident (constant
    index map). Residuals (post-activation gates, tanh(c), carried c)
    are written per tick so the backward never re-runs the matmul chain."""
    t = pl.program_id(0)
    nt = pl.num_programs(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    wh = wh_ref[...]
    nh = hidden
    for j in range(block_t):
        h_prev = h_scr[...]
        c_prev = c_scr[...]
        z = zx_ref[j].astype(jnp.float32) + jnp.dot(
            h_prev.astype(wh.dtype), wh,
            preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(z[:, :nh])
        f = jax.nn.sigmoid(z[:, nh:2 * nh])
        o = jax.nn.sigmoid(z[:, 2 * nh:3 * nh])
        g = jnp.tanh(z[:, 3 * nh:])
        c_raw = f * c_prev + i * g
        tc = jnp.tanh(c_raw)
        h_raw = o * tc
        m = mask_ref[j].astype(jnp.float32)  # (N, 1)
        h_new = m * h_raw + (1.0 - m) * h_prev
        c_new = m * c_raw + (1.0 - m) * c_prev
        h_scr[...] = h_new
        c_scr[...] = c_new
        ys_ref[j] = h_new.astype(ys_ref.dtype)
        gates_ref[j] = jnp.concatenate([i, f, o, g],
                                       axis=1).astype(gates_ref.dtype)
        tc_ref[j] = tc.astype(tc_ref.dtype)
        cc_ref[j] = c_new.astype(cc_ref.dtype)

    @pl.when(t == nt - 1)
    def _fin():
        hT_ref[...] = h_scr[...].astype(hT_ref.dtype)
        cT_ref[...] = c_scr[...].astype(cT_ref.dtype)


def _fused_forward(zx, h0, c0, wh, mask, block_t: int, interpret: bool):
    """zx (T, N, 4H) pre-projected inputs, mask (T, N, 1). T must be a
    multiple of block_t (the wrapper pads). Returns ys/hT/cT plus the
    backward residuals."""
    t_pad, n, g4 = zx.shape
    h = g4 // 4
    nt = t_pad // block_t
    vm = pl.ANY if interpret else pltpu.VMEM
    dt = zx.dtype

    kernel = functools.partial(_fwd_kernel, block_t=block_t, hidden=h)
    const2 = lambda t: (0, 0)
    tick3 = lambda t: (t, 0, 0)
    return pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((block_t, n, g4), tick3, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
            pl.BlockSpec((h, g4), const2, memory_space=vm),
            # (T, N, 1): trailing block dims equal the array dims, and m
            # broadcasts along lanes against the (N, H) carry
            pl.BlockSpec((block_t, n, 1), tick3, memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((block_t, n, h), tick3, memory_space=vm),
            pl.BlockSpec((block_t, n, g4), tick3, memory_space=vm),
            pl.BlockSpec((block_t, n, h), tick3, memory_space=vm),
            pl.BlockSpec((block_t, n, h), tick3, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_pad, n, h), dt),      # ys
            jax.ShapeDtypeStruct((t_pad, n, g4), dt),     # gates i|f|o|g
            jax.ShapeDtypeStruct((t_pad, n, h), dt),      # tanh(c_raw)
            jax.ShapeDtypeStruct((t_pad, n, h), dt),      # carried c
            jax.ShapeDtypeStruct((n, h), h0.dtype),       # hT
            jax.ShapeDtypeStruct((n, h), c0.dtype),       # cT
        ],
        scratch_shapes=[
            pltpu.VMEM((n, h), jnp.float32),
            pltpu.VMEM((n, h), jnp.float32),
        ],
        compiler_params=_dim_sem(1),
        interpret=interpret,
    )(zx, h0, c0, wh, mask)


def _bwd_kernel(dys_ref, dhT_ref, dcT_ref, gates_ref, tc_ref, cprev_ref,
                hprev_ref, mask_ref, wh_ref,
                dzx_ref, dwh_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, dwh_scr, *, block_t: int, hidden: int):
    """Reverse-time VJP of ``_fwd_kernel``. The grid's index maps walk T
    backwards; (dh, dc) and the dWh accumulator live in f32 scratch.
    Masked ticks pass (dh, dc) through untouched and contribute zero to
    dzx/dWh — the exact transpose of the carry-freezing forward."""
    k = pl.program_id(0)
    nt = pl.num_programs(0)

    @pl.when(k == 0)
    def _init():
        dh_scr[...] = dhT_ref[...].astype(jnp.float32)
        dc_scr[...] = dcT_ref[...].astype(jnp.float32)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    wh = wh_ref[...]
    nh = hidden
    for j in reversed(range(block_t)):
        m = mask_ref[j].astype(jnp.float32)  # (N, 1)
        dh = dh_scr[...] + dys_ref[j].astype(jnp.float32)
        dc = dc_scr[...]
        gts = gates_ref[j].astype(jnp.float32)
        i = gts[:, :nh]
        f = gts[:, nh:2 * nh]
        o = gts[:, 2 * nh:3 * nh]
        g = gts[:, 3 * nh:]
        tc = tc_ref[j].astype(jnp.float32)
        cp = cprev_ref[j].astype(jnp.float32)

        dh_raw = m * dh
        do = dh_raw * tc
        dc_raw = m * dc + dh_raw * o * (1.0 - tc * tc)
        di = dc_raw * g
        df = dc_raw * cp
        dg = dc_raw * i
        dz = jnp.concatenate([
            di * i * (1.0 - i),
            df * f * (1.0 - f),
            do * o * (1.0 - o),
            dg * (1.0 - g * g),
        ], axis=1)
        dzx_ref[j] = dz.astype(dzx_ref.dtype)
        hp = hprev_ref[j]
        dwh_scr[...] += jax.lax.dot_general(
            hp, dz.astype(hp.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dh_scr[...] = (1.0 - m) * dh + jax.lax.dot_general(
            dz.astype(wh.dtype), wh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dc_scr[...] = (1.0 - m) * dc + dc_raw * f

    @pl.when(k == nt - 1)
    def _fin():
        dwh_ref[...] = dwh_scr[...]
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_scr[...].astype(dc0_ref.dtype)


def _fused_backward(dys, dhT, dcT, gates, tcs, cprev, hprev, mask, wh,
                    block_t: int, interpret: bool):
    t_pad, n, h = dys.shape
    g4 = 4 * h
    nt = t_pad // block_t
    vm = pl.ANY if interpret else pltpu.VMEM

    kernel = functools.partial(_bwd_kernel, block_t=block_t, hidden=h)
    const2 = lambda k: (0, 0)
    rev3 = lambda k: (nt - 1 - k, 0, 0)
    return pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((block_t, n, h), rev3, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
            pl.BlockSpec((block_t, n, g4), rev3, memory_space=vm),
            pl.BlockSpec((block_t, n, h), rev3, memory_space=vm),
            pl.BlockSpec((block_t, n, h), rev3, memory_space=vm),
            pl.BlockSpec((block_t, n, h), rev3, memory_space=vm),
            pl.BlockSpec((block_t, n, 1), rev3, memory_space=vm),
            pl.BlockSpec((h, g4), const2, memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((block_t, n, g4), rev3, memory_space=vm),
            pl.BlockSpec((h, g4), const2, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
            pl.BlockSpec((n, h), const2, memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_pad, n, g4), dys.dtype),  # dzx
            jax.ShapeDtypeStruct((h, g4), jnp.float32),       # dWh
            jax.ShapeDtypeStruct((n, h), dhT.dtype),          # dh0
            jax.ShapeDtypeStruct((n, h), dcT.dtype),          # dc0
        ],
        scratch_shapes=[
            pltpu.VMEM((n, h), jnp.float32),
            pltpu.VMEM((n, h), jnp.float32),
            pltpu.VMEM((h, g4), jnp.float32),
        ],
        compiler_params=_dim_sem(1),
        interpret=interpret,
    )(dys, dhT, dcT, gates, tcs, cprev, hprev, mask, wh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _lstm_fused_core(zx, h0, c0, wh, mask, block_t, interpret):
    ys, _, _, _, hT, cT = _fused_forward(zx, h0, c0, wh, mask,
                                         block_t, interpret)
    return ys, hT, cT


def _core_fwd(zx, h0, c0, wh, mask, block_t, interpret):
    ys, gates, tcs, ccs, hT, cT = _fused_forward(zx, h0, c0, wh, mask,
                                                 block_t, interpret)
    return (ys, hT, cT), (h0, c0, wh, mask, ys, gates, tcs, ccs)


def _core_bwd(block_t, interpret, res, cts):
    h0, c0, wh, mask, ys, gates, tcs, ccs = res
    dys, dhT, dcT = cts
    # previous-tick carries, built once in XLA: prev(0) is the initial
    # state, prev(t) the tick-(t-1) outputs
    hprev = jnp.concatenate([h0[None].astype(ys.dtype), ys[:-1]], axis=0)
    cprev = jnp.concatenate([c0[None].astype(ccs.dtype), ccs[:-1]], axis=0)
    dzx, dwh, dh0, dc0 = _fused_backward(
        dys.astype(ys.dtype), dhT, dcT, gates, tcs, cprev, hprev, mask,
        wh, block_t, interpret)
    return (dzx, dh0, dc0, dwh.astype(wh.dtype), jnp.zeros_like(mask))


_lstm_fused_core.defvjp(_core_fwd, _core_bwd)


def lstm_fused(zx, h0, c0, wh, mask=None, *, block_t: int = _DEF_BLOCK_T,
               interpret: Optional[bool] = None):
    """Run the fused recurrence over pre-projected inputs.

    zx: (T, N, 4H) time-major ``x@Wx + b`` with gate-major [i|f|o|g]
    columns; h0/c0: (N, H); wh: (H, 4H); mask: optional (T, N) with the
    scan cell's semantics (masked ticks keep the previous carry).
    Returns (ys (T, N, H), hT, cT). Differentiable via a custom VJP
    whose backward is itself a fused reverse-time kernel.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t = zx.shape[0]
    n = zx.shape[1]
    if mask is None:
        mask3 = jnp.ones((t, n, 1), zx.dtype)
    else:
        mask3 = mask[:, :, None].astype(zx.dtype)
    pad = (-t) % block_t
    if pad:
        zx = jnp.pad(zx, ((0, pad), (0, 0), (0, 0)))
        # padded ticks are fully masked: carries pass through unchanged
        mask3 = jnp.pad(mask3, ((0, pad), (0, 0), (0, 0)))
    ys, hT, cT = _lstm_fused_core(zx, h0, c0, wh, mask3, block_t,
                                  interpret)
    return ys[:t], hT, cT
