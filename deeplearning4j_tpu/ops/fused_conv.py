"""Fused conv+BN+ReLU Pallas kernels for ResNet bottleneck blocks.

The accelerated-helper tier for the conv stack (reference concept: the
cuDNN per-layer helpers, CudnnConvolutionHelper.java:62 — SURVEY §2.4).
The measured ResNet50 64×64 step is HBM-bandwidth-bound
(PERF_ANALYSIS.md): XLA computes each BatchNormalization's batch
statistics in a separate pass over the conv output and applies
normalize+ReLU in another, so every activation crosses HBM ~3 extra
times per BN. benchmarks/bn_ceiling.py quantifies the ceiling: freezing
BN stats (pure elementwise) lifts 39.3k → 48.2k img/s/chip.

Design — two fusions per conv layer, both riding the one HBM pass the
conv already pays:
  * prologue: the normalize+ReLU of the PRODUCER's BatchNorm is applied
    to the input tile in VMEM right after load (BN normalize is just a
    per-channel scale+shift once stats are known), so the normalized
    activation is never materialized in HBM;
  * epilogue: per-channel (Σy, Σy²) of the conv output are accumulated
    while the output tile is still in VMEM, so the consumer's BN stats
    pass never re-reads y.

BN autodiff falls out for free: the kernels return (y, Σy, Σy²) and the
surrounding jnp code derives mean/var from the sums — the custom VJP
routes ``d(Σy)``/``d(Σy²)`` cotangents back into dy (broadcast + 2y·d),
so batch-stat gradients match jax.grad of the unfused math exactly.

1×1 convs (two of the three in every bottleneck) are matmuls over the
flattened (N·H·W, C) activation; the 3×3 runs per-image with the whole
(small) spatial plane resident in VMEM as 9 shifted matmuls. Both shapes
keep the MXU busy: at 64×64 inputs the spatial planes are tiny and the
channel counts large, exactly the regime where conv == matmul.

Like the flash-attention helper, everything falls back to plain XLA math
(`*_reference`) off-TPU, and the Pallas path runs in interpret mode in
tests so CPU CI exercises the same kernel code.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


# ---------------------------------------------------------------------------
# fused matmul (1×1 conv): y = relu?(x·s + b) @ W, + per-channel stats of y
# ---------------------------------------------------------------------------

def _mm_kernel(x_ref, w_ref, s_ref, b_ref, y_ref, st_ref, *,
               relu_in: bool, want_stats: bool, norm_in: bool,
               m_valid: int, bm: int):
    i = pl.program_id(1)                       # M tile (inner)
    x = x_ref[...]
    if norm_in:
        e = x.astype(jnp.float32) * s_ref[0] + b_ref[0]
        if relu_in:
            e = jnp.maximum(e, 0.0)
        e = e.astype(x_ref.dtype)
    else:
        e = x
    y = jnp.dot(e, w_ref[...],
                preferred_element_type=jnp.float32)       # (bm, bn)
    y_ref[...] = y.astype(y_ref.dtype)
    if want_stats:
        # rows beyond m_valid are padding: relu(0·s+b) is non-zero, so
        # mask them out of the stats (their y rows are sliced off by the
        # caller anyway)
        row = i * bm + lax.broadcasted_iota(jnp.int32, y.shape, 0)
        yv = jnp.where(row < m_valid, y, 0.0)
        st_ref[0, 0] = jnp.sum(yv, axis=0)
        st_ref[0, 1] = jnp.sum(yv * yv, axis=0)


def _mm_pallas(x2d, w, scale, shift, relu_in: bool, want_stats: bool,
               norm_in: bool, interpret: bool,
               out_dtype) -> Tuple[jax.Array, jax.Array]:
    m, cin = x2d.shape
    cout = w.shape[1]
    bm = min(1024, _round_up(m, 8))
    bn = min(512, cout)
    mp = _round_up(m, bm)
    if mp != m:
        x2d = jnp.pad(x2d, ((0, mp - m), (0, 0)))
    nm, nn = mp // bm, -(-cout // bn)
    kernel = functools.partial(
        _mm_kernel, relu_in=relu_in, want_stats=want_stats,
        norm_in=norm_in, m_valid=m, bm=bm)
    y, st = pl.pallas_call(
        kernel,
        grid=(nn, nm),                        # M innermost
        in_specs=[
            pl.BlockSpec((bm, cin), lambda j, i: (i, 0)),
            pl.BlockSpec((cin, bn), lambda j, i: (0, j)),
            pl.BlockSpec((1, cin), lambda j, i: (0, 0)),
            pl.BlockSpec((1, cin), lambda j, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            # per-(i,j) partial stats; reduced over i by the caller
            pl.BlockSpec((1, 2, bn), lambda j, i: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cout), out_dtype),
            jax.ShapeDtypeStruct((nm, 2, cout), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, w, scale[None, :], shift[None, :])
    if mp != m:
        y = y[:m]
    stats = jnp.sum(st, axis=0) if want_stats else None
    return y, stats


# ---------------------------------------------------------------------------
# fused 3×3 SAME conv: y = conv3x3(relu?(x·s + b)) + stats, per-image planes
# ---------------------------------------------------------------------------

def _c3_images_per_program(n: int, h: int, wd: int, cin: int,
                           itemsize: int = 2) -> int:
    """Images per grid program: enough for ~2k matmul rows (small planes
    would leave the MXU pipeline empty), capped so the padded input
    plane (``itemsize`` bytes/element — f32 planes cost 2× bf16, advisor
    r4) stays ≈1.5 MB of VMEM, and dividing the batch."""
    cap = max(1, int(1.5e6 / ((h + 2) * (wd + 2) * cin * itemsize)))
    bi = max(1, min(n, 2048 // max(1, h * wd), cap))
    while n % bi:
        bi -= 1
    return bi


def _c3_fits_vmem(h: int, wd: int, cin: int, cout: int,
                  itemsize: int = 2) -> bool:
    """Whether even a single-image 3×3 program fits the VMEM budget.

    The 3×3 kernels keep the whole padded (h+2)×(w+2)×Cin input plane
    plus the h×w×Cout f32 accumulator resident; at ImageNet-size planes
    (e.g. 224×224×64) that exceeds the ~16 MB of VMEM and the Pallas
    call fails at compile time. Beyond this budget the op falls back to
    the XLA reference math (advisor r3 low finding). `itemsize` is the
    compute dtype's bytes/element — f32 planes cost 2× bf16 (advisor r4
    low finding)."""
    plane = (h + 2) * (wd + 2) * cin * itemsize   # padded input plane
    # accumulator is tiled over cout in bn=min(512,cout) blocks — mirror
    # _c3_pallas, not the full cout (a 56×56×2048 layer tiles fine)
    acc = h * wd * min(512, cout) * 4             # f32 matmul accumulator
    return plane + acc <= 8e6

def _c3_kernel(x_ref, w_ref, s_ref, b_ref, y_ref, st_ref, *,
               relu_in: bool, want_stats: bool, norm_in: bool, h: int,
               wdt: int):
    if norm_in:
        x = x_ref[...].astype(jnp.float32)             # (bi, h, w, cin)
        e = x * s_ref[0, 0, 0] + b_ref[0, 0, 0]
        if relu_in:
            e = jnp.maximum(e, 0.0)
        e = e.astype(w_ref.dtype)
    else:
        e = x_ref[...].astype(w_ref.dtype)
    bi = e.shape[0]
    cin = e.shape[3]
    ep = jnp.pad(e, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((bi * h * wdt, y_ref.shape[3]), jnp.float32)
    for di in range(3):
        for dj in range(3):
            tap = ep[:, di:di + h, dj:dj + wdt, :].reshape(-1, cin)
            acc = acc + jnp.dot(tap, w_ref[di, dj],
                                preferred_element_type=jnp.float32)
    y_ref[...] = acc.reshape(bi, h, wdt, -1).astype(y_ref.dtype)
    if want_stats:
        st_ref[0, 0] = jnp.sum(acc, axis=0)
        st_ref[0, 1] = jnp.sum(acc * acc, axis=0)


def _c3_pallas(x4d, w, scale, shift, relu_in: bool, want_stats: bool,
               norm_in: bool, interpret: bool,
               out_dtype) -> Tuple[jax.Array, jax.Array]:
    n, h, wd, cin = x4d.shape
    cout = w.shape[3]
    bi = _c3_images_per_program(n, h, wd, cin,
                                max(x4d.dtype.itemsize, w.dtype.itemsize))
    bn = min(512, cout)
    ni, nn = n // bi, -(-cout // bn)
    kernel = functools.partial(_c3_kernel, relu_in=relu_in,
                               want_stats=want_stats, norm_in=norm_in,
                               h=h, wdt=wd)
    y, st = pl.pallas_call(
        kernel,
        grid=(nn, ni),
        in_specs=[
            pl.BlockSpec((bi, h, wd, cin), lambda j, i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, bn), lambda j, i: (0, 0, 0, j)),
            pl.BlockSpec((1, 1, 1, cin), lambda j, i: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cin), lambda j, i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bi, h, wd, bn), lambda j, i: (i, 0, 0, j)),
            pl.BlockSpec((1, 2, bn), lambda j, i: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, cout), out_dtype),
            jax.ShapeDtypeStruct((ni, 2, cout), jnp.float32),
        ],
        interpret=interpret,
    )(x4d, w, scale[None, None, None, :], shift[None, None, None, :])
    stats = jnp.sum(st, axis=0) if want_stats else None
    return y, stats


# ---------------------------------------------------------------------------
# backward kernels. All matmul-shaped work stays in Pallas: if any saved
# activation fed an XLA dot/conv, XLA would assign it that op's preferred
# (convolution) layout and insert relayout copies around every forward
# kernel — measured at +2 GB/step before these existed.
# ---------------------------------------------------------------------------

def _dyc(dy_ref, y_ref, a_ref, b_ref):
    """Total output cotangent: dy + dΣ + 2·y·dΣ² (stats chain rule)."""
    return (dy_ref[...].astype(jnp.float32) + a_ref[0]
            + 2.0 * y_ref[...].astype(jnp.float32) * b_ref[0])


def _bwd_merged_kernel(dy_ref, y_ref, wt_ref, x_ref, a_ref, b2_ref,
                       s_ref, sh_ref, dx_ref, dw_ref, st_ref, *,
                       relu_in: bool, norm_in: bool, m_valid: int,
                       bm: int):
    """Single pass over (dy, y, x): emits BOTH dx (per M tile) and the
    dW accumulation — the split dx/dW kernels each re-read the same
    dy/y/x streams, doubling backward HBM traffic."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dyc = _dyc(dy_ref, y_ref, a_ref, b2_ref)
    row = k * bm + lax.broadcasted_iota(jnp.int32, dyc.shape, 0)
    dyc = jnp.where(row < m_valid, dyc, 0.0).astype(dy_ref.dtype)
    de = jnp.dot(dyc, wt_ref[...],
                 preferred_element_type=jnp.float32)      # (bm, bci)
    xf = x_ref[...].astype(jnp.float32)
    if norm_in:
        s = s_ref[0]
        pre = xf * s + sh_ref[0]
        e = (jnp.maximum(pre, 0.0) if relu_in else pre) \
            .astype(x_ref.dtype)
        dpre = jnp.where(pre > 0.0, de, 0.0) if relu_in else de
        st_ref[0, 0] = jnp.sum(dpre * xf, axis=0)
        st_ref[0, 1] = jnp.sum(dpre, axis=0)
        dx_ref[...] = (dpre * s).astype(dx_ref.dtype)
    else:
        rowx = k * bm + lax.broadcasted_iota(jnp.int32, xf.shape, 0)
        e = jnp.where(rowx < m_valid, xf, 0.0).astype(x_ref.dtype)
        st_ref[0, 0] = jnp.zeros_like(st_ref[0, 0])
        st_ref[0, 1] = jnp.zeros_like(st_ref[0, 1])
        dx_ref[...] = de.astype(dx_ref.dtype)
    dw_ref[...] += lax.dot_general(
        e, dyc, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_merged_pallas(dy2, y2, wt, x2, dst, scale, shift, relu_in,
                       norm_in, interpret, out_dtype):
    m, cout = dy2.shape
    cin = wt.shape[1]
    # Co=2048 layers: halve the M tile so the f32 dyc temporary + the
    # full dW accumulator stay inside VMEM
    bm = min(512 if cout <= 1024 else 256, _round_up(m, 8))
    bci = min(512, cin)
    mp = _round_up(m, bm)
    if mp != m:
        dy2 = jnp.pad(dy2, ((0, mp - m), (0, 0)))
        y2 = jnp.pad(y2, ((0, mp - m), (0, 0)))
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    nm, nci = mp // bm, -(-cin // bci)
    kernel = functools.partial(_bwd_merged_kernel, relu_in=relu_in,
                               norm_in=norm_in, m_valid=m, bm=bm)
    dx, dw, st = pl.pallas_call(
        kernel,
        grid=(nci, nm),
        in_specs=[
            pl.BlockSpec((bm, cout), lambda i, k: (k, 0)),
            pl.BlockSpec((bm, cout), lambda i, k: (k, 0)),
            pl.BlockSpec((cout, bci), lambda i, k: (0, i)),
            pl.BlockSpec((bm, bci), lambda i, k: (k, i)),
            pl.BlockSpec((1, cout), lambda i, k: (0, 0)),
            pl.BlockSpec((1, cout), lambda i, k: (0, 0)),
            pl.BlockSpec((1, bci), lambda i, k: (0, i)),
            pl.BlockSpec((1, bci), lambda i, k: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bci), lambda i, k: (k, i)),
            pl.BlockSpec((bci, cout), lambda i, k: (i, 0)),
            pl.BlockSpec((1, 2, bci), lambda i, k: (k, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cin), out_dtype),
            jax.ShapeDtypeStruct((cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((nm, 2, cin), jnp.float32),
        ],
        interpret=interpret,
    )(dy2, y2, wt, x2, dst[0][None, :], dst[1][None, :],
      scale[None, :], shift[None, :])
    if mp != m:
        dx = dx[:m]
    st = jnp.sum(st, axis=0)
    return dx, dw, st[0], st[1]


def _c3_bwd_in_kernel(dy_ref, y_ref, wt_ref, x_ref, a_ref, b_ref, s_ref,
                      sh_ref, dx_ref, st_ref, *, relu_in: bool,
                      norm_in: bool, h: int, wdt: int):
    """3×3 SAME bwd-input: de = conv(dyc, flip(W)ᵀ), then BN/ReLU bwd."""
    dyc = (dy_ref[...].astype(jnp.float32) + a_ref[0, 0, 0]
           + 2.0 * y_ref[...].astype(jnp.float32) * b_ref[0, 0, 0])
    dyc = dyc.astype(dy_ref.dtype)
    bi = dyc.shape[0]
    cout = dyc.shape[3]
    dp = jnp.pad(dyc, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((bi * h * wdt, dx_ref.shape[3]), jnp.float32)
    for di in range(3):
        for dj in range(3):
            tap = dp[:, di:di + h, dj:dj + wdt, :].reshape(-1, cout)
            acc = acc + jnp.dot(tap, wt_ref[di, dj],
                                preferred_element_type=jnp.float32)
    de = acc.reshape(bi, h, wdt, -1)
    if norm_in:
        xf = x_ref[...].astype(jnp.float32)
        s = s_ref[0, 0, 0]
        pre = xf * s + sh_ref[0, 0, 0]
        dpre = jnp.where(pre > 0.0, de, 0.0) if relu_in else de
        st_ref[0, 0] = jnp.sum(dpre * xf, axis=(0, 1, 2))
        st_ref[0, 1] = jnp.sum(dpre, axis=(0, 1, 2))
        dx_ref[...] = (dpre * s).astype(dx_ref.dtype)
    else:
        st_ref[0, 0] = jnp.zeros_like(st_ref[0, 0])
        st_ref[0, 1] = jnp.zeros_like(st_ref[0, 1])
        dx_ref[...] = de.astype(dx_ref.dtype)


def _c3_bwd_w_kernel(x_ref, dy_ref, y_ref, s_ref, b_ref, a_ref, b2_ref,
                     dw_ref, *, relu_in: bool, norm_in: bool, h: int,
                     wdt: int):
    """3×3 bwd-filter: dW[t] += shifted(e)ᵀ @ dyc, per tap."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    if norm_in:
        e = x_ref[...].astype(jnp.float32) * s_ref[0, 0, 0] \
            + b_ref[0, 0, 0]
        if relu_in:
            e = jnp.maximum(e, 0.0)
        e = e.astype(x_ref.dtype)
    else:
        e = x_ref[...]
    dyc = (dy_ref[...].astype(jnp.float32) + a_ref[0, 0, 0]
           + 2.0 * y_ref[...].astype(jnp.float32) * b2_ref[0, 0, 0])
    dyc = dyc.astype(dy_ref.dtype).reshape(-1, dy_ref.shape[3])
    cin = e.shape[3]
    ep = jnp.pad(e, ((0, 0), (1, 1), (1, 1), (0, 0)))
    for di in range(3):
        for dj in range(3):
            tap = ep[:, di:di + h, dj:dj + wdt, :].reshape(-1, cin)
            dw_ref[di, dj] += lax.dot_general(
                tap, dyc, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)


def _c3_bwd_merged_kernel(dy_ref, y_ref, wt_ref, x_ref, a_ref, b_ref,
                          s_ref, sh_ref, dx_ref, dw_ref, st_ref, *,
                          relu_in: bool, h: int, wdt: int):
    """3×3 merged backward (one pass over dy/y/x): dx via 9 taps of the
    flipped-transposed filter, dW accumulated per tap, BN/ReLU backward
    in the epilogue."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dyc = (dy_ref[...].astype(jnp.float32) + a_ref[0, 0, 0]
           + 2.0 * y_ref[...].astype(jnp.float32) * b_ref[0, 0, 0])
    dyc = dyc.astype(dy_ref.dtype)
    bi = dyc.shape[0]
    cout = dyc.shape[3]
    cin = x_ref.shape[3]
    xf = x_ref[...].astype(jnp.float32)
    s = s_ref[0, 0, 0]
    pre = xf * s + sh_ref[0, 0, 0]
    e = (jnp.maximum(pre, 0.0) if relu_in else pre).astype(x_ref.dtype)

    dp = jnp.pad(dyc, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((bi * h * wdt, cin), jnp.float32)
    for di in range(3):
        for dj in range(3):
            tap = dp[:, di:di + h, dj:dj + wdt, :].reshape(-1, cout)
            acc = acc + jnp.dot(tap, wt_ref[di, dj],
                                preferred_element_type=jnp.float32)
    de = acc.reshape(bi, h, wdt, cin)
    dpre = jnp.where(pre > 0.0, de, 0.0) if relu_in else de
    st_ref[0, 0] = jnp.sum(dpre * xf, axis=(0, 1, 2))
    st_ref[0, 1] = jnp.sum(dpre, axis=(0, 1, 2))
    dx_ref[...] = (dpre * s).astype(dx_ref.dtype)

    dyc2 = dyc.reshape(-1, cout)
    ep = jnp.pad(e, ((0, 0), (1, 1), (1, 1), (0, 0)))
    for di in range(3):
        for dj in range(3):
            tap = ep[:, di:di + h, dj:dj + wdt, :].reshape(-1, cin)
            dw_ref[di, dj] += lax.dot_general(
                tap, dyc2, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)


def _c3_bwd_merged_pallas(x, dy, y, w, dst, scale, shift, relu_in,
                          interpret, out_dtype):
    n, h, wd, cin = x.shape
    cout = dy.shape[3]
    bi = _c3_images_per_program(n, h, wd, cin,
                                max(x.dtype.itemsize, w.dtype.itemsize))
    ni = n // bi
    wt = w[::-1, ::-1].transpose(0, 1, 3, 2)
    a4 = dst[0][None, None, None, :]
    b4 = dst[1][None, None, None, :]
    s4 = scale[None, None, None, :]
    sh4 = shift[None, None, None, :]
    kernel = functools.partial(_c3_bwd_merged_kernel, relu_in=relu_in,
                               h=h, wdt=wd)
    dx, dw, st = pl.pallas_call(
        kernel,
        grid=(ni,),
        in_specs=[
            pl.BlockSpec((bi, h, wd, cout), lambda k: (k, 0, 0, 0)),
            pl.BlockSpec((bi, h, wd, cout), lambda k: (k, 0, 0, 0)),
            pl.BlockSpec((3, 3, cout, cin), lambda k: (0, 0, 0, 0)),
            pl.BlockSpec((bi, h, wd, cin), lambda k: (k, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cout), lambda k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cout), lambda k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cin), lambda k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cin), lambda k: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bi, h, wd, cin), lambda k: (k, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 2, cin), lambda k: (k, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, cin), out_dtype),
            jax.ShapeDtypeStruct((3, 3, cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((ni, 2, cin), jnp.float32),
        ],
        interpret=interpret,
    )(dy, y, wt, x, a4, b4, s4, sh4)
    st = jnp.sum(st, axis=0)
    return dx, dw, st[0], st[1]


def _c3_bwd_pallas(x, dy, y, w, dst, scale, shift, relu_in, norm_in,
                   interpret, out_dtype):
    n, h, wd, cin = x.shape
    cout = dy.shape[3]
    bi = _c3_images_per_program(n, h, wd, cin,
                                max(x.dtype.itemsize, w.dtype.itemsize))
    ni = n // bi
    bci = min(512, cin)
    wt = w[::-1, ::-1].transpose(0, 1, 3, 2)       # flip + IO swap
    a4 = dst[0][None, None, None, :]
    b4 = dst[1][None, None, None, :]
    s4 = scale[None, None, None, :]
    sh4 = shift[None, None, None, :]

    kin = functools.partial(_c3_bwd_in_kernel, relu_in=relu_in,
                            norm_in=norm_in, h=h, wdt=wd)
    dx, st = pl.pallas_call(
        kin,
        grid=(-(-cin // bci), ni),
        in_specs=[
            pl.BlockSpec((bi, h, wd, cout), lambda i, k: (k, 0, 0, 0)),
            pl.BlockSpec((bi, h, wd, cout), lambda i, k: (k, 0, 0, 0)),
            pl.BlockSpec((3, 3, cout, bci), lambda i, k: (0, 0, 0, i)),
            pl.BlockSpec((bi, h, wd, bci), lambda i, k: (k, 0, 0, i)),
            pl.BlockSpec((1, 1, 1, cout), lambda i, k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cout), lambda i, k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, bci), lambda i, k: (0, 0, 0, i)),
            pl.BlockSpec((1, 1, 1, bci), lambda i, k: (0, 0, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((bi, h, wd, bci), lambda i, k: (k, 0, 0, i)),
            pl.BlockSpec((1, 2, bci), lambda i, k: (k, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, cin), out_dtype),
            jax.ShapeDtypeStruct((ni, 2, cin), jnp.float32),
        ],
        interpret=interpret,
    )(dy, y, wt, x, a4, b4, s4, sh4)
    st = jnp.sum(st, axis=0)

    bco = min(256, cout)
    kw = functools.partial(_c3_bwd_w_kernel, relu_in=relu_in,
                           norm_in=norm_in, h=h, wdt=wd)
    dw = pl.pallas_call(
        kw,
        grid=(-(-cout // bco), ni),
        in_specs=[
            pl.BlockSpec((bi, h, wd, cin), lambda j, k: (k, 0, 0, 0)),
            pl.BlockSpec((bi, h, wd, bco), lambda j, k: (k, 0, 0, j)),
            pl.BlockSpec((bi, h, wd, bco), lambda j, k: (k, 0, 0, j)),
            pl.BlockSpec((1, 1, 1, cin), lambda j, k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, cin), lambda j, k: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, bco), lambda j, k: (0, 0, 0, j)),
            pl.BlockSpec((1, 1, 1, bco), lambda j, k: (0, 0, 0, j)),
        ],
        out_specs=pl.BlockSpec((3, 3, cin, bco),
                               lambda j, k: (0, 0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((3, 3, cin, cout), jnp.float32),
        interpret=interpret,
    )(x, dy, y, s4, sh4, a4, b4)
    return dx, dw, st[0], st[1]


# ---------------------------------------------------------------------------
# reference math (XLA path; also the VJP recompute)
# ---------------------------------------------------------------------------

def _norm_in(x, scale, shift, relu_in: bool, norm_in: bool):
    if not norm_in:
        return x
    e = x.astype(jnp.float32) * scale + shift
    if relu_in:
        e = jnp.maximum(e, 0.0)
    return e.astype(x.dtype)


def _conv_reference(x, w, scale, shift, relu_in, norm_in, stride):
    e = _norm_in(x, scale, shift, relu_in, norm_in)
    if w.ndim == 2:                                     # 1×1
        if stride != 1:
            e = e[:, ::stride, ::stride, :]
        y = jnp.einsum("nhwc,co->nhwo", e, w,
                       preferred_element_type=jnp.float32)
    else:                                               # 3×3 SAME, stride 1
        y = lax.conv_general_dilated(
            e, w, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
    sums = jnp.stack([jnp.sum(y, axis=(0, 1, 2)),
                      jnp.sum(y * y, axis=(0, 1, 2))])
    return y.astype(x.dtype), sums


@jax.custom_vjp
def _gram(e):
    """G = eᵀe over all leading axes, f32-accumulated from the compute
    dtype (bf16 on the MXU). The custom VJP exists because einsum with
    ``preferred_element_type=f32`` cannot be transposed by autodiff (an
    f32 cotangent against bf16 operands); de = e·(dG + dGᵀ) is the
    exact gradient."""
    return jnp.einsum("nhwa,nhwb->ab", e, e,
                      preferred_element_type=jnp.float32)


def _gram_fwd(e):
    return _gram(e), e


def _gram_bwd(e, dg):
    d = (dg + dg.T).astype(e.dtype)
    return (jnp.einsum("ab,nhwb->nhwa", d, e),)


_gram.defvjp(_gram_fwd, _gram_bwd)


def conv_bn_stats_xla(x, w, scale, shift, relu_in: bool = True,
                      norm_in: bool = True, stride: int = 1,
                      interpret=None):
    """XLA-native sibling of ``fused_conv_bn_act`` — same
    ``(y, (Σy, Σy²))`` contract, plain jnp ops (no custom calls, no
    custom VJP), with **Gram-matrix statistics** for expanding 1×1
    convs (round 4, the measured XLA-side replacement VERDICT r3 #1
    allows):

    For ``y = e @ W``:  ``Σᵢ yᵢ = (Σᵢ eᵢ) @ W``  and
    ``Σᵢ yᵢ² = diag(Wᵀ (eᵀe) W)`` — so the batch statistics of the
    OUTPUT are computed from the (smaller) input side plus a
    weights-sized contraction, and XLA never re-reads the Cout-sized
    activation for a stats pass. Worth it exactly when Cout > Cin (the
    bottleneck's expand and downsample projections — the 4f-channel
    activations that dominate BN-stat traffic); other convs use the
    direct reduction, which autodiff also differentiates exactly.
    ``interpret`` is accepted and ignored (signature parity)."""
    e = _norm_in(x, scale, shift, relu_in, norm_in)
    f32 = jnp.float32
    w = w.astype(e.dtype)       # compute-dtype matmul/conv (MXU bf16)
    if w.ndim == 2:
        if stride != 1:
            e = e[:, ::stride, ::stride, :]
        n, h, wd, cin = e.shape
        cout = w.shape[1]
        # 4-D einsum, NOT a reshape-to-2D matmul: the flatten forces a
        # physical relayout between conv-tiled and matmul-tiled forms
        # (measured −8k img/s on the ResNet50 step). No
        # preferred_element_type — its transpose rule would pair an f32
        # cotangent with the bf16 weights and fail to differentiate.
        y = jnp.einsum("nhwc,co->nhwo", e, w)
        import os
        # DL4J_GRAM / DL4J_GRAM_T are read at TRACE time: a jitted step
        # freezes the choice — call jax.clear_caches() after changing
        # them (they exist for benchmarking sweeps, not runtime toggles)
        mode = os.environ.get("DL4J_GRAM", "auto")
        # The Gram pays an M·cin² MXU contraction to avoid an
        # M·cout·2-byte stat read. The naive roofline (bf16 183 TF/s vs
        # 819 GB/s) suggests profit until cin² ≈ 450·cout, but measured
        # e2e the wide-cin stages give the win back (T=400 → 41.4k vs
        # T=64 → 43.5-45.2k img/s — PERF_ANALYSIS.md r4): the direct
        # stat reductions XLA fuses for those stages are cheaper than
        # the extra contraction. 64 is the measured optimum.
        thresh = float(os.environ.get("DL4J_GRAM_T", "64"))  # host-sync-ok: env var
        use_gram = (mode == "always" or
                    (mode == "auto" and cout > cin
                     and cin * cin <= thresh * cout))
        if use_gram:
            wf = w.astype(f32)
            gram = _gram(e)
            colsum = jnp.sum(e.astype(f32), axis=(0, 1, 2))
            s1 = colsum @ wf
            s2 = jnp.einsum("ac,ab,bc->c", wf, gram, wf)
            sums = jnp.stack([s1, s2])
        else:
            yf = y.astype(f32)
            sums = jnp.stack([jnp.sum(yf, axis=(0, 1, 2)),
                              jnp.sum(yf * yf, axis=(0, 1, 2))])
        return y.astype(x.dtype), sums
    y = lax.conv_general_dilated(
        e, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    yf = y.astype(f32)
    sums = jnp.stack([jnp.sum(yf, axis=(0, 1, 2)),
                      jnp.sum(yf * yf, axis=(0, 1, 2))])
    return y.astype(x.dtype), sums


# ---------------------------------------------------------------------------
# public op: custom VJP, pallas fwd / XLA bwd
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_conv_bn_act(x, w, scale, shift, relu_in: bool = True,
                      norm_in: bool = True, stride: int = 1,
                      interpret: Optional[bool] = None):
    """y = conv(relu?(x·scale + shift)) ⊕ per-channel (Σy, Σy²).

    ``w`` (Cin, Cout) selects the 1×1 matmul path (with optional spatial
    ``stride``); ``w`` (3, 3, Cin, Cout) the SAME 3×3 path. Returns
    ``(y, stats)`` with ``stats`` float32 (2, Cout). The stats output is
    differentiable, which is what makes the surrounding BatchNorm's
    batch-statistics gradient exact."""
    y, st = _fused_fwd_impl(x, w, scale, shift, relu_in, norm_in, stride,
                            interpret)
    return y, st


def _fused_fwd_impl(x, w, scale, shift, relu_in, norm_in, stride,
                    interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if w.ndim == 2:
        if stride != 1:
            x = x[:, ::stride, ::stride, :]
        n, h, wd, cin = x.shape
        y2, st = _mm_pallas(x.reshape(-1, cin), w, scale, shift, relu_in,
                            True, norm_in, interpret, x.dtype)
        return y2.reshape(n, h, wd, -1), st
    n, h, wd, cin = x.shape
    if not _c3_fits_vmem(h, wd, cin, w.shape[3],
                         max(x.dtype.itemsize, w.dtype.itemsize)):
        return _conv_reference(x, w, scale, shift, relu_in, norm_in, 1)
    return _c3_pallas(x, w, scale, shift, relu_in, True, norm_in,
                      interpret, x.dtype)


def _fused_fwd_rule(x, w, scale, shift, relu_in, norm_in, stride,
                    interpret):
    y, st = _fused_fwd_impl(x, w, scale, shift, relu_in, norm_in, stride,
                            interpret)
    return (y, st), (x, w, scale, shift, y)


def _fused_bwd_rule(relu_in, norm_in, stride, interpret, res, cots):
    """Pallas backward: the normalized input is recomputed tile-wise
    (flash-style — it was never materialized), the stats cotangents fold
    into dy inside the kernels, and the BN/ReLU backward (mask, dγ/dβ
    sums, input rescale) rides the bwd-input matmul's epilogue. Keeping
    the backward matmuls in Pallas matters beyond the fusion itself: if
    a saved activation fed an XLA dot/conv, XLA would assign it that
    op's preferred layout and relayout-copy around every forward
    kernel."""
    x, w, scale, shift, y = res
    dy, dst = cots
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if dst is None:
        dst = jnp.zeros((2, y.shape[-1]), jnp.float32)
    dst = dst.astype(jnp.float32)

    xs = x[:, ::stride, ::stride, :] if (w.ndim == 2 and stride != 1) \
        else x
    cin = xs.shape[-1]
    cout = y.shape[-1]

    if w.ndim == 4 and not _c3_fits_vmem(
            xs.shape[1], xs.shape[2], cin, cout,
            max(x.dtype.itemsize, w.dtype.itemsize)):
        # oversized spatial plane: the whole op ran on the XLA reference
        # path — differentiate that same math
        def _ref(x_, w_, s_, b_):
            return _conv_reference(x_, w_, s_, b_, relu_in, norm_in, 1)
        _, vjp = jax.vjp(_ref, x, w, scale, shift)
        return vjp((dy, dst))

    if w.ndim == 2:
        dy2 = dy.reshape(-1, cout)
        y2 = y.reshape(-1, cout)
        xs2 = xs.reshape(-1, cin)
        dxs2, dw, dscale, dshift = _bwd_merged_pallas(
            dy2, y2, w.T, xs2, dst, scale, shift, relu_in, norm_in,
            interpret, x.dtype)
        dxs = dxs2.reshape(xs.shape)
    elif cin <= 384 and norm_in:
        # merged single-pass 3×3 backward; at f=512 the full dW
        # accumulator no longer fits VMEM next to the planes → split
        dxs, dw, dscale, dshift = _c3_bwd_merged_pallas(
            xs, dy, y, w, dst, scale, shift, relu_in, interpret,
            x.dtype)
    else:
        dxs, dw, dscale, dshift = _c3_bwd_pallas(
            xs, dy, y, w, dst, scale, shift, relu_in, norm_in,
            interpret, x.dtype)

    if not norm_in:
        dscale = jnp.zeros_like(scale)
        dshift = jnp.zeros_like(shift)

    if w.ndim == 2 and stride != 1:
        dx = jnp.zeros(x.shape, x.dtype)
        dx = dx.at[:, ::stride, ::stride, :].set(dxs)
    else:
        dx = dxs
    return dx, dw.astype(w.dtype), dscale, dshift


fused_conv_bn_act.defvjp(_fused_fwd_rule, _fused_bwd_rule)


# ---------------------------------------------------------------------------
# BN helpers shared by the fused block layer
# ---------------------------------------------------------------------------

def stats_to_scale_shift(stats, count, gamma, beta, eps):
    """(Σy, Σy²) → the (scale, shift) form of BN normalize+affine, plus
    (mean, var) for the running-average update. Biased variance, exactly
    like jnp.var / the BatchNormalization layer."""
    f32 = jnp.float32
    mean = stats[0].astype(f32) / count
    var = jnp.maximum(stats[1].astype(f32) / count - mean * mean, 0.0)
    inv = gamma.astype(f32) * lax.rsqrt(var + eps)
    return inv, beta.astype(f32) - mean * inv, mean, var
