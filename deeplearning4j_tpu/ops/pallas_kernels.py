"""Pallas TPU kernels — the "accelerated layer helper" tier.

The reference accelerates its hot layers with hand-written cuDNN helpers
loaded reflectively (deeplearning4j-cuda/.../BaseCudnnHelper.java:1,
ConvolutionLayer.java:75-85 — SURVEY §2.4). The TPU analog: XLA already
lowers conv/BN/LSTM onto the MXU, so helpers are only written where a
fused kernel beats XLA's default lowering. Attention is the headline case:
the blockwise (flash) kernel below keeps the running softmax in VMEM and
never materializes the (Tq, Tk) score matrix in HBM.

Layout: q/k/v are (N, H, T, Dh) inside the kernel (the layer-facing
wrapper accepts the framework-standard (N, T, H, Dh)). The grid is
(batch, head, q-block); each program streams the full K/V for its head
through VMEM in ``block_k`` chunks with an online softmax.

Like the reference's helper SPI, failure is safe: `attention()` silently
falls back to the plain XLA path when shapes/platform don't fit the
kernel (ConvolutionLayer.java:173 helperCountFail analog).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Large-finite instead of -inf: -inf scores make softmax VJPs emit NaN for
# fully-masked rows (matches nn/layers/attention.py's choice).
_NEG = float(jnp.finfo(jnp.float32).min) / 2.0  # host-sync-ok: finfo constant

_DEF_BLOCK_Q = 1024  # tuned on v5e: 16k-seq causal attn 21.5ms vs 84ms at 128
_DEF_BLOCK_K = 1024


def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *, causal: bool,
                      scale: float):
    """One (q-block, k-block) tile of the online softmax. The k-block
    axis is the innermost SEQUENTIAL grid dim; the running (m, l, acc)
    live in VMEM scratch across its iterations, so K/V stream from HBM
    block by block and VMEM stays O(block) at any sequence length (the
    pre-round-4 kernel kept the whole K/V resident and died at 16k)."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: tiles fully above the diagonal contribute nothing
    live = (ki * bk <= (qi + 1) * bq - 1) if causal \
        else (ki == ki)  # always-true traced pred

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)                # (bq, dh)
        k = k_ref[0, 0].astype(jnp.float32)                # (bk, dh)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        kvalid = mask_ref[0, 0] > 0.0
        s = jnp.where(kvalid[None, :], s, _NEG)
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        m_prev = m_scr[:, :1]                              # (bq, 1)
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        # A row that never saw a valid key keeps m == _NEG: its p values
        # were exp(0)=1 garbage, so zero the output (matching the XLA
        # reference) rather than emitting mean(v).
        valid = m > (_NEG * 0.5)
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o = jnp.where(valid, acc_scr[...] / l_safe, 0.0)
        o_ref[0, 0] = o.astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(valid, m + jnp.log(l_safe), _NEG)


# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _dim_sem(n: int):
    return _CompilerParams(
        dimension_semantics=("parallel",) * (n - 1) + ("arbitrary",))


def _flash_forward(q, k, v, mask, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    n, h, tq, dh = q.shape
    tk = k.shape[2]
    scale = 1.0 / float(dh) ** 0.5  # host-sync-ok: static shape
    grid = (n, h, tq // block_q, tk // block_k)
    vm = pl.ANY if interpret else pltpu.VMEM

    kernel = functools.partial(_flash_fwd_kernel, causal=causal,
                               scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, qi, ki: (i, j, ki, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, qi, ki: (i, j, ki, 0),
                         memory_space=vm),
            # (n, 1, tk) so the block's trailing dims stay legal for the
            # TPU lowering (last two block dims divisible by (8, 128) or
            # equal to the array dims)
            pl.BlockSpec((1, 1, block_k),
                         lambda i, j, qi, ki: (i, 0, ki),
                         memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
            # trailing singleton for the same block-shape constraint
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, tq, dh), q.dtype),
            jax.ShapeDtypeStruct((n, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, dh), jnp.float32),
        ],
        compiler_params=_dim_sem(4),
        interpret=interpret,
    )(q, k, v, mask[:, None, :])
    return out, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention(q, k, v, mask, causal, block_q, block_k, interpret,
                     bwd_impl):
    out, _ = _flash_forward(q, k, v, mask, causal, block_q, block_k,
                            interpret)
    return out


def _flash_fwd_rule(q, k, v, mask, causal, block_q, block_k, interpret,
                    bwd_impl):
    out, lse = _flash_forward(q, k, v, mask, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          causal: bool, scale: float):
    """dK/dV for one key block: the query-block axis is the innermost
    sequential grid dim, accumulating into VMEM scratch — P is recomputed
    from the saved logsumexp, never materialized in HBM."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = ((qi + 1) * bq - 1 >= ki * bk) if causal else (qi == qi)

    @pl.when(live)
    def _tile():
        kb = k_ref[0, 0].astype(jnp.float32)               # (bk, dh)
        vb = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32)                # (bq, dh)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                                # (bq, 1)
        delta = delta_ref[0, 0]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask_ref[0, 0][None, :] > 0.0, s, _NEG)
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        p = jnp.exp(s - lse)
        p = jnp.where(lse > (_NEG * 0.5), p, 0.0)          # (bq, bk)
        dv_scr[...] += lax.dot_general(
            p, do, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[...] += lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, dq_scr, *, causal: bool,
                         scale: float):
    """dQ for one query block: key blocks stream on the sequential grid
    dim, accumulating into VMEM scratch."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (ki * bk <= (qi + 1) * bq - 1) if causal else (ki == ki)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)                # (bq, dh)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                                # (bq, 1)
        delta = delta_ref[0, 0]
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask_ref[0, 0][None, :] > 0.0, s, _NEG)
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        p = jnp.exp(s - lse)
        p = jnp.where(lse > (_NEG * 0.5), p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] += jnp.dot(ds, k,
                               preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_backward_pallas(q, k, v, mask, out, lse, do, causal: bool,
                           block_q: int, block_k: int, interpret: bool):
    """Pallas dq/dk/dv (VERDICT r3 #2 — both passes in kernels, like the
    reference's CudnnLSTMHelper accelerating fwd AND bwd). The tiny
    delta = rowsum(dO ⊙ O) precompute stays in XLA (one fused elementwise
    pass); everything matmul-shaped runs on the MXU in Pallas."""
    n, h, tq, dh = q.shape
    tk = k.shape[2]
    scale = 1.0 / float(dh) ** 0.5  # host-sync-ok: static shape
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                # (n, h, tq, 1)
    lse4 = lse[..., None]                                  # (n, h, tq, 1)
    mask3 = mask[:, None, :]                               # (n, 1, tk)
    vm = pl.ANY if interpret else pltpu.VMEM

    kernel = functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                               scale=scale)
    dk, dv = pl.pallas_call(
        kernel,
        grid=(n, h, tk // block_k, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, ki, qi: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, ki, qi: (i, j, ki, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, ki, qi: (i, j, ki, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k),
                         lambda i, j, ki, qi: (i, 0, ki),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, ki, qi: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda i, j, ki, qi: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda i, j, ki, qi: (i, j, qi, 0),
                         memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, ki, qi: (i, j, ki, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, ki, qi: (i, j, ki, 0),
                         memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, tk, dh), k.dtype),
            jax.ShapeDtypeStruct((n, h, tk, dh), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dh), jnp.float32),
            pltpu.VMEM((block_k, dh), jnp.float32),
        ],
        compiler_params=_dim_sem(4),
        interpret=interpret,
    )(q, k, v, mask3, do, lse4, delta)

    kernel = functools.partial(_flash_bwd_dq_kernel, causal=causal,
                               scale=scale)
    dq = pl.pallas_call(
        kernel,
        grid=(n, h, tq // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, qi, ki: (i, j, ki, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k, dh),
                         lambda i, j, qi, ki: (i, j, ki, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_k),
                         lambda i, j, qi, ki: (i, 0, ki),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda i, j, qi, ki: (i, j, qi, 0),
                         memory_space=vm),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, dh),
                               lambda i, j, qi, ki: (i, j, qi, 0),
                               memory_space=vm),
        out_shape=jax.ShapeDtypeStruct((n, h, tq, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        compiler_params=_dim_sem(4),
        interpret=interpret,
    )(q, k, v, mask3, do, lse4, delta)
    return dq, dk, dv


def _flash_bwd_rule(causal, block_q, block_k, interpret, bwd_impl, res,
                    do):
    """Flash backward from saved (O, logsumexp) — dq/dk/dv Pallas kernels
    (``_flash_backward_pallas``); P is recomputed from the normalizer
    instead of being saved. ``bwd_impl`` ("pallas"/"xla", the explicit
    flash_attention parameter) takes precedence; when None the
    ``DL4J_FLASH_BWD=xla`` env override selects the jnp/scan reference
    implementation (also used by equivalence tests). The env var is read
    at TRACE time — a jitted train step freezes the choice; call
    ``jax.clear_caches()`` after changing it (advisor r4: pass bwd_impl
    for programmatic control instead)."""
    import os
    q, k, v, mask, out, lse = res
    if bwd_impl is None:
        bwd_impl = os.environ.get("DL4J_FLASH_BWD", "pallas")
    if bwd_impl != "xla":
        dq, dk, dv = _flash_backward_pallas(
            q, k, v, mask, out, lse, do, causal, block_q, block_k,
            interpret)
        return dq, dk, dv, jnp.zeros_like(mask)
    return _flash_bwd_xla(causal, block_q, block_k, interpret, res, do)


def _flash_bwd_xla(causal, block_q, block_k, interpret, res, do):
    """jnp/scan blockwise backward: the pre-round-4 VJP, kept as the
    reference implementation the Pallas kernels are tested against.
    Chunked over k blocks with lax.scan so peak memory is
    O(Tq * block_k) per (batch, head), not O(Tq * Tk)."""
    q, k, v, mask, out, lse = res
    dh = q.shape[-1]
    scale = 1.0 / float(dh) ** 0.5  # host-sync-ok: static shape
    f32 = jnp.float32
    qf, kf, vf, dof = (x.astype(f32) for x in (q, k, v, do))
    delta = jnp.sum(dof * out.astype(f32), axis=-1)        # (n, h, tq)
    tq, tk = q.shape[2], k.shape[2]

    def p_block(kb):
        """(n, h, tq, bk) probability block at k offset kb*block_k."""
        ks = lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=2)
        s = jnp.einsum("nhqd,nhkd->nhqk", qf, ks) * scale
        mk = lax.dynamic_slice_in_dim(mask, kb * block_k, block_k, axis=1)
        s = jnp.where(mk[:, None, None, :] > 0, s, _NEG)
        if causal:
            qpos = jnp.arange(tq)[:, None]
            kpos = kb * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(kpos <= qpos, s, _NEG)
        p = jnp.exp(s - lse[..., None])
        # fully-masked rows carry lse == _NEG: exp(s - lse) degenerates to
        # 1 there; their true probabilities (and grads) are zero
        p = jnp.where(lse[..., None] > (_NEG * 0.5), p, 0.0)
        return p, ks

    def scan_body(dq, kb):
        p, ks = p_block(kb)
        vs = lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=2)
        dp = jnp.einsum("nhqd,nhkd->nhqk", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("nhqk,nhkd->nhqd", ds, ks)
        dv_b = jnp.einsum("nhqk,nhqd->nhkd", p, dof)
        dk_b = jnp.einsum("nhqk,nhqd->nhkd", ds, qf)
        return dq, (dk_b, dv_b)

    nk = tk // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = lax.scan(scan_body, dq0, jnp.arange(nk))
    # (nk, n, h, bk, d) -> (n, h, tk, d)
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(kf.shape)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(vf.shape)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(mask))


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _pad_len(t: int, block: int) -> int:
    return (-t) % block


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    block_q: int = _DEF_BLOCK_Q,
                    block_k: int = _DEF_BLOCK_K,
                    interpret: Optional[bool] = None,
                    bwd_impl: Optional[str] = None):
    """Blockwise (flash) attention on (N, T, H, Dh) tensors.

    Drop-in for nn.layers.attention.scaled_dot_product_attention. ``mask``
    is the (N, T_k) key-validity mask. Sequences are padded to the block
    size internally (padding is masked out, query padding sliced off).
    ``interpret`` defaults to True off-TPU so tests exercise the same
    kernel on the CPU mesh. ``bwd_impl`` selects the backward
    implementation explicitly ("pallas" kernels or the "xla" jnp/scan
    reference); None defers to the ``DL4J_FLASH_BWD`` env override
    (default pallas).
    """
    if bwd_impl not in (None, "pallas", "xla"):
        raise ValueError(f"bwd_impl must be 'pallas'/'xla'/None, "
                         f"got {bwd_impl!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, tq, h, dh = q.shape
    tk = k.shape[1]
    block_q = min(block_q, max(tq, 1))
    block_k = min(block_k, max(tk, 1))
    if not interpret:
        # Mosaic constraints: q blocks land in the sublane dim (multiple
        # of 8); the mask's dynamic k-slice is in the lane dim (multiple
        # of 128). Sequences are padded up to the block size below.
        block_q = max(8, (block_q + 7) // 8 * 8)
        block_k = max(128, (block_k + 127) // 128 * 128)


    # NTHD -> NHTD
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if mask is None:
        mask = jnp.ones((n, tk), jnp.float32)
    mask = mask.astype(jnp.float32)

    pq, pk = _pad_len(tq, block_q), _pad_len(tk, block_k)
    if pq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pk), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, pk)))

    out = _flash_attention(qt, kt, vt, mask, causal, block_q, block_k,
                           interpret, bwd_impl)
    if pq:
        out = out[:, :, :tq, :]
    return jnp.swapaxes(out, 1, 2)                          # NHTD -> NTHD


# Measured on v5e (benchmarks/attn_crossover.py, bf16 fwd+bwd, 12 heads
# Dh=64): plain XLA wins at T<=512 (the full score matrix is small and
# XLA fuses it into large batched MXU matmuls; the flash grid degenerates
# to tiny single-block programs), the streaming kernel wins from T=1024
# on. Re-measured after the head-trailing score-order change sped the
# XLA path up: 1024: 9.6 vs 9.9 ms; 2048: 13.4 vs 14.8; 4096: 20.7 vs
# 24.5 — narrower, same crossover, and plain XLA still OOMs on the
# O(T^2) scores at long T.
_FLASH_MIN_SEQ = 1024


def attention(q, k, v, mask=None, causal: bool = False,
              prefer_flash: Optional[bool] = None):
    """Helper-SPI dispatch (the reflective cuDNN-hook analog): use the
    Pallas kernel when it applies AND the sequence is long enough to pay
    for streaming, else the plain XLA lowering (the same dual-tier
    policy as the reference's cuDNN helper + helperCountFail fallback,
    ConvolutionLayer.java:173)."""
    from deeplearning4j_tpu.nn.layers.attention import (
        scaled_dot_product_attention)
    if prefer_flash is None:
        prefer_flash = (jax.default_backend() == "tpu"
                        and max(q.shape[1], k.shape[1]) >= _FLASH_MIN_SEQ)
    if not prefer_flash:
        return scaled_dot_product_attention(q, k, v, mask=mask,
                                            causal=causal)
    try:
        return flash_attention(q, k, v, mask=mask, causal=causal)
    except Exception:          # helper fallback, never fatal
        return scaled_dot_product_attention(q, k, v, mask=mask,
                                            causal=causal)
