"""Pallas TPU kernels — the "accelerated layer helper" tier.

The reference accelerates its hot layers with hand-written cuDNN helpers
loaded reflectively (deeplearning4j-cuda/.../BaseCudnnHelper.java:1,
ConvolutionLayer.java:75-85 — SURVEY §2.4). The TPU analog: XLA already
lowers conv/BN/LSTM onto the MXU, so helpers are only written where a
fused kernel beats XLA's default lowering. Attention is the headline case:
the blockwise (flash) kernel below keeps the running softmax in VMEM and
never materializes the (Tq, Tk) score matrix in HBM.

Layout: q/k/v are (N, H, T, Dh) inside the kernel (the layer-facing
wrapper accepts the framework-standard (N, T, H, Dh)). The grid is
(batch, head, q-block); each program streams the full K/V for its head
through VMEM in ``block_k`` chunks with an online softmax.

Like the reference's helper SPI, failure is safe: `attention()` silently
falls back to the plain XLA path when shapes/platform don't fit the
kernel (ConvolutionLayer.java:173 helperCountFail analog).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Large-finite instead of -inf: -inf scores make softmax VJPs emit NaN for
# fully-masked rows (matches nn/layers/attention.py's choice).
_NEG = float(jnp.finfo(jnp.float32).min) / 2.0

_DEF_BLOCK_Q = 1024  # tuned on v5e: 16k-seq causal attn 21.5ms vs 84ms at 128
_DEF_BLOCK_K = 1024


def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *,
                      block_k: int, causal: bool, scale: float):
    q = q_ref[0, 0].astype(jnp.float32)                    # (bq, dh)
    bq, dh = q.shape
    tk = k_ref.shape[2]
    nk = tk // block_k
    qi = pl.program_id(2)

    m0 = jnp.full((bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, dh), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        kvalid = mask_ref[0, 0, pl.ds(kb * block_k, block_k)] > 0.0
        s = jnp.where(kvalid[None, :], s, _NEG)
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            kpos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    # A row that never saw a valid key keeps m == _NEG: its p values were
    # exp(0)=1 garbage, so zero the output (matching the XLA reference)
    # rather than emitting mean(v).
    valid = m > (_NEG * 0.5)
    l_safe = jnp.where(l > 0.0, l, 1.0)
    o = jnp.where(valid[:, None], acc / l_safe[:, None], 0.0)
    o_ref[0, 0] = o.astype(o_ref.dtype)
    lse_ref[0, 0, :, 0] = jnp.where(valid, m + jnp.log(l_safe), _NEG)


def _flash_forward(q, k, v, mask, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    n, h, tq, dh = q.shape
    tk = k.shape[2]
    scale = 1.0 / float(dh) ** 0.5
    grid = (n, h, tq // block_q)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, qi: (i, j, qi, 0),
                         memory_space=pl.ANY if interpret
                         else pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, dh), lambda i, j, qi: (i, j, 0, 0),
                         memory_space=pl.ANY if interpret
                         else pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, dh), lambda i, j, qi: (i, j, 0, 0),
                         memory_space=pl.ANY if interpret
                         else pltpu.VMEM),
            # (n, 1, tk) so the block's trailing dims equal the array's
            # (TPU lowering constraint: last two block dims divisible by
            # (8, 128) or equal to the array dims)
            pl.BlockSpec((1, 1, tk), lambda i, j, qi: (i, 0, 0),
                         memory_space=pl.ANY if interpret
                         else pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, dh),
                         lambda i, j, qi: (i, j, qi, 0),
                         memory_space=pl.ANY if interpret
                         else pltpu.VMEM),
            # trailing singleton for the same block-shape constraint
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda i, j, qi: (i, j, qi, 0),
                         memory_space=pl.ANY if interpret
                         else pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, tq, dh), q.dtype),
            jax.ShapeDtypeStruct((n, h, tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask[:, None, :])
    return out, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention(q, k, v, mask, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, mask, causal, block_q, block_k,
                            interpret)
    return out


def _flash_fwd_rule(q, k, v, mask, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, mask, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, do):
    """Flash backward from saved (O, logsumexp): P is recomputed from the
    normalizer instead of being saved — the standard flash-attention VJP.
    Chunked over k blocks with lax.scan so peak memory is
    O(Tq * block_k) per (batch, head), not O(Tq * Tk)."""
    q, k, v, mask, out, lse = res
    dh = q.shape[-1]
    scale = 1.0 / float(dh) ** 0.5
    f32 = jnp.float32
    qf, kf, vf, dof = (x.astype(f32) for x in (q, k, v, do))
    delta = jnp.sum(dof * out.astype(f32), axis=-1)        # (n, h, tq)
    tq, tk = q.shape[2], k.shape[2]

    def p_block(kb):
        """(n, h, tq, bk) probability block at k offset kb*block_k."""
        ks = lax.dynamic_slice_in_dim(kf, kb * block_k, block_k, axis=2)
        s = jnp.einsum("nhqd,nhkd->nhqk", qf, ks) * scale
        mk = lax.dynamic_slice_in_dim(mask, kb * block_k, block_k, axis=1)
        s = jnp.where(mk[:, None, None, :] > 0, s, _NEG)
        if causal:
            qpos = jnp.arange(tq)[:, None]
            kpos = kb * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(kpos <= qpos, s, _NEG)
        p = jnp.exp(s - lse[..., None])
        # fully-masked rows carry lse == _NEG: exp(s - lse) degenerates to
        # 1 there; their true probabilities (and grads) are zero
        p = jnp.where(lse[..., None] > (_NEG * 0.5), p, 0.0)
        return p, ks

    def scan_body(dq, kb):
        p, ks = p_block(kb)
        vs = lax.dynamic_slice_in_dim(vf, kb * block_k, block_k, axis=2)
        dp = jnp.einsum("nhqd,nhkd->nhqk", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("nhqk,nhkd->nhqd", ds, ks)
        dv_b = jnp.einsum("nhqk,nhqd->nhkd", p, dof)
        dk_b = jnp.einsum("nhqk,nhqd->nhkd", ds, qf)
        return dq, (dk_b, dv_b)

    nk = tk // block_k
    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = lax.scan(scan_body, dq0, jnp.arange(nk))
    # (nk, n, h, bk, d) -> (n, h, tk, d)
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(kf.shape)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(vf.shape)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(mask))


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _pad_len(t: int, block: int) -> int:
    return (-t) % block


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    block_q: int = _DEF_BLOCK_Q,
                    block_k: int = _DEF_BLOCK_K,
                    interpret: Optional[bool] = None):
    """Blockwise (flash) attention on (N, T, H, Dh) tensors.

    Drop-in for nn.layers.attention.scaled_dot_product_attention. ``mask``
    is the (N, T_k) key-validity mask. Sequences are padded to the block
    size internally (padding is masked out, query padding sliced off).
    ``interpret`` defaults to True off-TPU so tests exercise the same
    kernel on the CPU mesh.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, tq, h, dh = q.shape
    tk = k.shape[1]
    block_q = min(block_q, max(tq, 1))
    block_k = min(block_k, max(tk, 1))
    if not interpret:
        # Mosaic constraints: q blocks land in the sublane dim (multiple
        # of 8); the mask's dynamic k-slice is in the lane dim (multiple
        # of 128). Sequences are padded up to the block size below.
        block_q = max(8, (block_q + 7) // 8 * 8)
        block_k = max(128, (block_k + 127) // 128 * 128)

    # NTHD -> NHTD
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if mask is None:
        mask = jnp.ones((n, tk), jnp.float32)
    mask = mask.astype(jnp.float32)

    pq, pk = _pad_len(tq, block_q), _pad_len(tk, block_k)
    if pq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pk), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, pk)))

    out = _flash_attention(qt, kt, vt, mask, causal, block_q, block_k,
                           interpret)
    if pq:
        out = out[:, :, :tq, :]
    return jnp.swapaxes(out, 1, 2)                          # NHTD -> NTHD


def attention(q, k, v, mask=None, causal: bool = False,
              prefer_flash: Optional[bool] = None):
    """Helper-SPI dispatch (the reflective cuDNN-hook analog): use the
    Pallas kernel when it applies, else the plain XLA lowering."""
    from deeplearning4j_tpu.nn.layers.attention import (
        scaled_dot_product_attention)
    if prefer_flash is None:
        prefer_flash = jax.default_backend() == "tpu"
    if not prefer_flash:
        return scaled_dot_product_attention(q, k, v, mask=mask,
                                            causal=causal)
    try:
        return flash_attention(q, k, v, mask=mask, causal=causal)
    except Exception:          # helper fallback, never fatal
        return scaled_dot_product_attention(q, k, v, mask=mask,
                                            causal=causal)
