"""Activation functions.

TPU-native analog of the ND4J activation registry the reference consumes
(``org.nd4j.linalg.activations.Activation``; used throughout
deeplearning4j-nn layer configs). Each activation is a pure jnp function —
derivatives come from ``jax.grad``, so there is no per-activation backprop
method. XLA fuses these into the adjacent matmul/conv, which is exactly the
elementwise-fusion the TPU HBM-bandwidth budget wants.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_enum


@register_enum
class Activation(enum.Enum):
    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    TANH = "tanh"
    HARDTANH = "hardtanh"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    SOFTMAX = "softmax"
    LOGSOFTMAX = "logsoftmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    MISH = "mish"
    CUBE = "cube"
    THRESHOLDEDRELU = "thresholdedrelu"

    def apply(self, x: jnp.ndarray) -> jnp.ndarray:
        return _FNS[self](x)


def _rational_tanh(x):
    # Rational approximation of tanh (reference ships RationalTanh as a
    # cheap tanh; on TPU the VPU makes real tanh cheap, but we keep the
    # function for numerical parity): 1.7159 * tanh(2x/3) approximated.
    a = jnp.clip(x * (2.0 / 3.0), -3.0, 3.0)
    p = a * (27.0 + a * a) / (27.0 + 9.0 * a * a)
    return 1.7159 * p


_FNS = {
    Activation.IDENTITY: lambda x: x,
    Activation.RELU: jax.nn.relu,
    Activation.RELU6: jax.nn.relu6,
    Activation.LEAKYRELU: lambda x: jax.nn.leaky_relu(x, 0.01),
    Activation.ELU: jax.nn.elu,
    Activation.SELU: jax.nn.selu,
    # exact (erf) GELU: what Keras/torch/BERT mean by "gelu"; jax.nn.gelu
    # defaults to the tanh approximation, which costs ~1e-4 import-
    # fidelity error per FFN against real Keras models
    Activation.GELU: lambda x: jax.nn.gelu(x, approximate=False),
    Activation.SIGMOID: jax.nn.sigmoid,
    Activation.HARDSIGMOID: jax.nn.hard_sigmoid,
    Activation.TANH: jnp.tanh,
    Activation.HARDTANH: lambda x: jnp.clip(x, -1.0, 1.0),
    Activation.RATIONALTANH: _rational_tanh,
    Activation.RECTIFIEDTANH: lambda x: jnp.maximum(jnp.tanh(x), 0.0),
    Activation.SOFTMAX: lambda x: jax.nn.softmax(x, axis=-1),
    Activation.LOGSOFTMAX: lambda x: jax.nn.log_softmax(x, axis=-1),
    Activation.SOFTPLUS: jax.nn.softplus,
    Activation.SOFTSIGN: jax.nn.soft_sign,
    Activation.SWISH: jax.nn.swish,
    Activation.MISH: jax.nn.mish,
    Activation.CUBE: lambda x: x ** 3,
    Activation.THRESHOLDEDRELU: lambda x: jnp.where(x > 1.0, x, 0.0),
}
