"""Random-walk sequence generators.

Analog of the reference's graph/iterator/RandomWalkIterator.java and
WeightedRandomWalkIterator.java (SURVEY §2.8): fixed-length walks from
every vertex, with NoEdgeHandling semantics (self-loop on dead ends).
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

from deeplearning4j_tpu.graph.api import Graph


class RandomWalkIterator:
    """Uniform-neighbor walks, one walk per starting vertex per pass."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.walks_per_vertex = walks_per_vertex

    def _next_step(self, rng, cur: int) -> int:
        nbrs = self.graph.get_connected_vertices(cur)
        if not nbrs:
            return cur   # NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
        return nbrs[rng.integers(len(nbrs))]

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        n = self.graph.num_vertices()
        for _rep in range(self.walks_per_vertex):
            order = rng.permutation(n)
            for start in order:
                walk = [int(start)]
                cur = int(start)
                for _ in range(self.walk_length - 1):
                    cur = int(self._next_step(rng, cur))
                    walk.append(cur)
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transition probabilities."""

    def _next_step(self, rng, cur: int) -> int:
        edges = self.graph.get_edges_out(cur)
        if not edges:
            return cur
        weights = np.asarray([w for _d, w in edges], np.float64)
        s = weights.sum()
        if s <= 0:
            return edges[rng.integers(len(edges))][0]
        return edges[rng.choice(len(edges), p=weights / s)][0]


class Node2VecWalkIterator(RandomWalkIterator):
    """node2vec biased second-order walks (reference: models/node2vec/ —
    SURVEY §2.7). Return parameter ``p`` penalizes immediate backtracking,
    in-out parameter ``q`` interpolates BFS-like (q>1) vs DFS-like (q<1)
    exploration (Grover & Leskovec 2016, public algorithm)."""

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 0, walks_per_vertex: int = 1):
        super().__init__(graph, walk_length, seed, walks_per_vertex)
        self.p = float(p)
        self.q = float(q)

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        n = self.graph.num_vertices()
        nbr_sets = [set(self.graph.get_connected_vertices(v))
                    for v in range(n)]
        for _rep in range(self.walks_per_vertex):
            order = rng.permutation(n)
            for start in order:
                walk = [int(start)]
                prev = None
                cur = int(start)
                for _ in range(self.walk_length - 1):
                    nbrs = self.graph.get_connected_vertices(cur)
                    if not nbrs:
                        walk.append(cur)
                        continue
                    if prev is None:
                        nxt = nbrs[rng.integers(len(nbrs))]
                    else:
                        w = np.empty(len(nbrs), np.float64)
                        prev_nbrs = nbr_sets[prev]
                        for i, x in enumerate(nbrs):
                            if x == prev:
                                w[i] = 1.0 / self.p
                            elif x in prev_nbrs:
                                w[i] = 1.0
                            else:
                                w[i] = 1.0 / self.q
                        nxt = nbrs[rng.choice(len(nbrs), p=w / w.sum())]
                    prev, cur = cur, int(nxt)
                    walk.append(cur)
                yield walk
