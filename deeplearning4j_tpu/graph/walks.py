"""Random-walk sequence generators.

Analog of the reference's graph/iterator/RandomWalkIterator.java and
WeightedRandomWalkIterator.java (SURVEY §2.8): fixed-length walks from
every vertex, with NoEdgeHandling semantics (self-loop on dead ends).
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

from deeplearning4j_tpu.graph.api import Graph


class RandomWalkIterator:
    """Uniform-neighbor walks, one walk per starting vertex per pass."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.walks_per_vertex = walks_per_vertex

    def _next_step(self, rng, cur: int) -> int:
        nbrs = self.graph.get_connected_vertices(cur)
        if not nbrs:
            return cur   # NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
        return nbrs[rng.integers(len(nbrs))]

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        n = self.graph.num_vertices()
        for _rep in range(self.walks_per_vertex):
            order = rng.permutation(n)
            for start in order:
                walk = [int(start)]
                cur = int(start)
                for _ in range(self.walk_length - 1):
                    cur = int(self._next_step(rng, cur))
                    walk.append(cur)
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transition probabilities."""

    def _next_step(self, rng, cur: int) -> int:
        edges = self.graph.get_edges_out(cur)
        if not edges:
            return cur
        weights = np.asarray([w for _d, w in edges], np.float64)
        s = weights.sum()
        if s <= 0:
            return edges[rng.integers(len(edges))][0]
        return edges[rng.choice(len(edges), p=weights / s)][0]
