"""Graph API + in-memory implementation.

Analog of the reference's graph/api/IGraph + graph/graph/Graph.java
(SURVEY §2.8): integer-indexed vertices with optional values, directed or
undirected weighted edges, adjacency queries.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclasses.dataclass
class Edge:
    src: int
    dst: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """reference: graph/graph/Graph.java (adjacency-list in-memory)."""

    def __init__(self, n_vertices: int, directed: bool = False):
        self.directed = directed
        self._vertices = [Vertex(i) for i in range(n_vertices)]
        self._adj: List[List[Tuple[int, float]]] = [
            [] for _ in range(n_vertices)]

    @classmethod
    def from_edges(cls, n_vertices: int,
                   edges: Iterable[Tuple[int, int]],
                   directed: bool = False) -> "Graph":
        g = cls(n_vertices, directed)
        for e in edges:
            g.add_edge(*e)
        return g

    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def set_vertex_value(self, idx: int, value: Any):
        self._vertices[idx].value = value

    def add_edge(self, src: int, dst: int, weight: float = 1.0):
        self._adj[src].append((dst, weight))
        if not self.directed:
            self._adj[dst].append((src, weight))

    def get_connected_vertices(self, idx: int) -> List[int]:
        return [d for d, _w in self._adj[idx]]

    def get_edges_out(self, idx: int) -> List[Tuple[int, float]]:
        return list(self._adj[idx])

    def degree(self, idx: int) -> int:
        return len(self._adj[idx])
