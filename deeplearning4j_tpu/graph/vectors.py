"""GraphVectors: serving API over trained vertex embeddings.

Analog of the reference's graph/models/GraphVectors + embeddings holder
(SURVEY §2.8): lookup, similarity, nearest vertices, save/load.
"""

from __future__ import annotations

import json
from typing import List

import numpy as np


class GraphVectors:
    def __init__(self, vectors: np.ndarray):
        self._vectors = np.asarray(vectors, np.float32)

    @classmethod
    def from_deepwalk(cls, dw) -> "GraphVectors":
        n = dw.graph.num_vertices() if dw.graph else dw.vocab.num_words()
        mat = np.stack([dw.get_vertex_vector(v) for v in range(n)])
        return cls(mat)

    def num_vertices(self) -> int:
        return self._vectors.shape[0]

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self._vectors[v]

    def similarity(self, a: int, b: int) -> float:
        va, vb = self._vectors[a], self._vectors[b]
        den = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / den) if den else 0.0

    def vertices_nearest(self, v: int, top_n: int = 10) -> List[int]:
        norms = np.linalg.norm(self._vectors, axis=1, keepdims=True)
        unit = self._vectors / np.maximum(norms, 1e-12)
        sims = unit @ unit[v]
        order = np.argsort(-sims)
        return [int(i) for i in order if i != v][:top_n]

    def save(self, path: str):
        np.savez_compressed(path, vectors=self._vectors)

    @classmethod
    def load(cls, path: str) -> "GraphVectors":
        import os
        data = np.load(path if os.path.exists(path) else path + ".npz")
        return cls(data["vectors"])
