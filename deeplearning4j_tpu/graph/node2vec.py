"""Node2Vec: biased-walk graph embeddings.

Analog of the reference's ``models/node2vec/`` (SURVEY §2.7): DeepWalk's
SkipGram training over second-order p/q-biased walks. The training hot
loop is the same batched jitted SkipGram kernel (nlp/skipgram.py); only
the walk distribution differs.
"""

from __future__ import annotations

from deeplearning4j_tpu.graph.api import Graph
from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.walks import Node2VecWalkIterator


class Node2Vec(DeepWalk):
    """DeepWalk with p/q-biased walk generation (return parameter ``p``,
    in-out parameter ``q``)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 1,
                 p: float = 1.0, q: float = 1.0, **kwargs):
        super().__init__(vector_size=vector_size, window_size=window_size,
                         walk_length=walk_length,
                         walks_per_vertex=walks_per_vertex, **kwargs)
        self.p = p
        self.q = q

    def fit(self, graph_or_walks):
        if isinstance(graph_or_walks, Graph):
            if self.graph is not graph_or_walks:
                self.initialize(graph_or_walks)
            walks = Node2VecWalkIterator(
                graph_or_walks, self.walk_length, p=self.p, q=self.q,
                seed=self.seed, walks_per_vertex=self.walks_per_vertex)
            sequences = [[str(v) for v in walk] for walk in walks]
            return super(DeepWalk, self).fit(sequences)
        return super().fit(graph_or_walks)
