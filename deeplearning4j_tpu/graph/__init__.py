"""Graph embeddings — analog of deeplearning4j-graph (SURVEY §2.8)."""

from deeplearning4j_tpu.graph.api import Edge, Graph, Vertex
from deeplearning4j_tpu.graph.walks import (
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.vectors import GraphVectors

__all__ = ["Graph", "Vertex", "Edge", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "DeepWalk", "GraphVectors"]
