"""Graph embeddings — analog of deeplearning4j-graph (SURVEY §2.8)."""

from deeplearning4j_tpu.graph.api import Edge, Graph, Vertex
from deeplearning4j_tpu.graph.walks import (
    Node2VecWalkIterator,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.node2vec import Node2Vec
from deeplearning4j_tpu.graph.vectors import GraphVectors

__all__ = ["Graph", "Vertex", "Edge", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "Node2VecWalkIterator",
           "DeepWalk", "Node2Vec", "GraphVectors"]
