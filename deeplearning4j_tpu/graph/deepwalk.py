"""DeepWalk: graph vertex embeddings via SkipGram over random walks.

Analog of the reference's graph/models/deepwalk/DeepWalk.java:33
(``fit():96``; hierarchical softmax via GraphHuffman — SURVEY §2.8).
Walk generation is the host-side producer; the training hot loop is the
same jitted batched SkipGram kernel as Word2Vec (nlp/skipgram.py), with
vertex indices as "words". Degree-based frequencies replace corpus counts
for the Huffman tree/negative table.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph
from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


class DeepWalk(SequenceVectors):
    """reference: DeepWalk.Builder — vectorSize, windowSize, walkLength,
    learningRate; fit(GraphWalkIterator)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 1,
                 use_hierarchic_softmax: bool = True, **kwargs):
        kwargs.setdefault("layer_size", vector_size)
        kwargs.setdefault("window_size", window_size)
        kwargs.setdefault("min_word_frequency", 1)
        kwargs.setdefault("use_hierarchic_softmax", use_hierarchic_softmax)
        super().__init__(**kwargs)
        self.vector_size = kwargs["layer_size"]
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.graph: Optional[Graph] = None

    def initialize(self, graph: Graph):
        """Pre-build vocab over all vertices (reference:
        DeepWalk.initialize(IGraph)) so embeddings exist for isolated
        vertices too; 'frequency' = degree + 1."""
        self.graph = graph
        seqs = [[str(v)] * (graph.degree(v) + 1)
                for v in range(graph.num_vertices())]
        self.build_vocab(seqs)
        self._init_tables()
        return self

    def fit(self, graph_or_walks):
        if isinstance(graph_or_walks, Graph):
            if self.graph is not graph_or_walks:
                self.initialize(graph_or_walks)
            walks = RandomWalkIterator(
                graph_or_walks, self.walk_length, seed=self.seed,
                walks_per_vertex=self.walks_per_vertex)
        else:
            walks = graph_or_walks
        sequences = [[str(v) for v in walk] for walk in walks]
        return super().fit(sequences)

    # ---- vertex-flavored lookup API -------------------------------------
    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self.get_word_vector(str(v))

    def similarity_vertices(self, a: int, b: int) -> float:
        return self.similarity(str(a), str(b))

    def vertices_nearest(self, v: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self.words_nearest(str(v), top_n)]
