"""Shared example bootstrap: put the repo root on sys.path so every
walkthrough runs as ``python examples/<name>.py`` without installing
the package. Imported for its side effect (`import _bootstrap` — the
script's own directory is first on sys.path, so this resolves here)."""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def pin_cpu_mesh(n_devices: int) -> None:
    """Pin the example to an ``n_devices``-wide virtual CPU mesh BEFORE
    jax initializes. The image's TPU shim exports JAX_PLATFORMS=axon
    ambiently — that is NOT a user choice, so it is overridden; an
    explicit user setting like ``JAX_PLATFORMS=tpu`` IS respected (the
    example then needs enough real devices or exits with a message)."""
    ambient = os.environ.get("JAX_PLATFORMS")
    if ambient not in (None, "", "axon", "cpu"):
        return                      # explicit user platform choice
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # only fill in the device count the user did NOT choose
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{n_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")


def smoke() -> bool:
    """True when the DL4J_EXAMPLE_SMOKE env knob is set: examples
    shrink shapes/step counts to seconds-scale and skip interactive
    waits, so the test suite's smoke tier can assert each walkthrough
    still runs to rc=0 (see tests/test_examples.py,
    ``./runtests.sh --examples``)."""
    return os.environ.get("DL4J_EXAMPLE_SMOKE", "") not in ("", "0")


def sized(full, tiny):
    """Pick a tunable's full-size value, or the tiny smoke-tier value
    when DL4J_EXAMPLE_SMOKE is set."""
    return tiny if smoke() else full


def need_devices(n_devices: int) -> None:
    """Actionable exit when the backend came up too small (instead of an
    opaque mesh reshape error)."""
    import jax
    have = len(jax.devices())
    if have < n_devices:
        raise SystemExit(
            f"this example needs {n_devices} devices, found {have} — "
            "unset JAX_PLATFORMS to use the default virtual CPU mesh, "
            "or run on a host with enough chips")
