"""Shared example bootstrap: put the repo root on sys.path so every
walkthrough runs as ``python examples/<name>.py`` without installing
the package. Imported for its side effect (`import _bootstrap` — the
script's own directory is first on sys.path, so this resolves here)."""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
