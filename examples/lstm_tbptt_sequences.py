"""Sequence classification with LSTM + truncated BPTT + stateful
inference (the reference's RNN tutorial workflow, SURVEY §5.7).

Run: JAX_PLATFORMS=cpu python examples/lstm_tbptt_sequences.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import UciSequenceDataSetIterator
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(42)
            .updater(Adam(5e-3))
            .list()
            .layer(LSTM(n_out=24, activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=6, loss=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(1, 60))
            .backprop_type("tbptt")      # 60-step seqs → 3 chunks of 20
            .tbptt_fwd_length(20)
            .build())

    model = MultiLayerNetwork(conf).init()
    train = UciSequenceDataSetIterator(32, train=True)
    test = UciSequenceDataSetIterator(32, train=False)
    model.fit(train, epochs=_bootstrap.sized(5, 1))
    ev = model.evaluate(test)
    print(f"test accuracy: {ev.accuracy():.3f}")

    # stateful streaming inference (reference: rnnTimeStep)
    batch = next(iter(test))
    carries = None
    for t in range(10):  # feed one timestep at a time
        step = batch.features[:, t, :]
        out, carries = model.rnn_time_step(step, carries)
    print("streamed 10 steps; last output shape:", out.shape)


if __name__ == "__main__":
    main()
