"""Word2Vec embeddings: fit, query, serialize (the reference's
Word2Vec tutorial workflow — SURVEY §3.6).

Run: JAX_PLATFORMS=cpu python examples/word2vec_embeddings.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

from deeplearning4j_tpu.nlp import serializer as WordVectorSerializer
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

CORPUS = [
    "the cat sat on the mat",
    "the dog sat on the rug",
    "a cat chased the mouse",
    "the dog chased the cat",
    "mice fear the cat",
    "dogs and cats are pets",
] * _bootstrap.sized(50, 4)


def main():
    w2v = Word2Vec(layer_size=32, window_size=3, negative=5,
                   min_word_frequency=1, epochs=_bootstrap.sized(5, 1), seed=7)
    w2v.fit(CORPUS)

    print("vocab size:", w2v.vocab.num_words())
    print("nearest to 'cat':", w2v.words_nearest("cat", top_n=3))
    print("sim(cat, dog) =", round(w2v.similarity("cat", "dog"), 3))

    WordVectorSerializer.write_word_vectors(w2v, "/tmp/vecs.txt")
    loaded = WordVectorSerializer.read_word_vectors("/tmp/vecs.txt")
    print("reloaded", loaded.has_word("cat"))


if __name__ == "__main__":
    main()
