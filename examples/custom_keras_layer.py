"""Custom Keras-layer registration — import a model containing a layer
the converter registry does not know, by registering your own converter
(reference: KerasLayer.registerCustomLayer + the custom-layer docs).

Run: JAX_PLATFORMS=cpu python examples/custom_keras_layer.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import os
import tempfile

import numpy as np

from deeplearning4j_tpu.modelimport.keras import (
    import_keras_model_and_weights,
)
from deeplearning4j_tpu.modelimport.layers import (
    Converted,
    register_custom_layer,
)


def main():
    import keras
    from keras import layers as L

    # a user-defined Keras layer (here: a scaled tanh)
    @keras.saving.register_keras_serializable(package="demo")
    class ScaledTanh(L.Layer):
        def __init__(self, scale=2.0, **kw):
            super().__init__(**kw)
            self.scale = scale

        def call(self, x):
            return keras.ops.tanh(x) * self.scale

        def get_config(self):
            return {**super().get_config(), "scale": self.scale}

    keras.utils.set_random_seed(0)
    inp = keras.Input((6,))
    x = L.Dense(8)(inp)
    x = ScaledTanh(scale=2.0)(x)
    out = L.Dense(3)(x)
    km = keras.Model(inp, out)
    path = os.path.join(tempfile.mkdtemp(), "custom.keras")
    km.save(path)

    # without registration: a clear unsupported-layer error
    try:
        import_keras_model_and_weights(path)
    except ValueError as e:
        print("unregistered:", str(e)[:72], "...")

    # register a converter mapping ScaledTanh onto framework layers
    # (the pure function becomes a LambdaLayer)
    from deeplearning4j_tpu.nn.layers.misc import LambdaLayer

    def scaled_tanh(cfg, _version):
        import jax.numpy as jnp
        s = float(cfg.get("scale", 1.0))
        return Converted(layer=LambdaLayer(
            fn=lambda x: jnp.tanh(x) * s))

    register_custom_layer("ScaledTanh", scaled_tanh)
    model = import_keras_model_and_weights(path)

    x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
    want = np.asarray(km(x))
    got = np.asarray(model.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    print("custom layer imports exactly: max err",
          float(np.max(np.abs(got - want))))


if __name__ == "__main__":
    main()
