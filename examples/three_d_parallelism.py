"""3D parallelism — data x tensor x pipeline in ONE mesh.

The standard TPU-pod deployment: the batch shards over the ``data``
axis (GSPMD), each transformer stage runs Megatron column/row-parallel
over ``model`` (GSPMD), and layers pipeline over ``pipe`` with the
circular/interleaved schedule (shard_map, manual over the pipe axis
only). The pipelined loss is golden-checked against the sequential
stack, and the sharded checkpoint restores onto a DIFFERENT 3D layout.

Run: python examples/three_d_parallelism.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

_bootstrap.pin_cpu_mesh(8)

import jax

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.pipeline import (
    PIPE_AXIS,
    PipelinedTransformerLM,
)


def main():
    dp, tp, pp = 2, 2, 2
    _bootstrap.need_devices(dp * tp * pp)
    devices = np.asarray(jax.devices()[: dp * tp * pp])
    mesh = Mesh(devices.reshape(dp, tp, pp), ("data", "model", PIPE_AXIS))
    print(f"mesh: {dict(mesh.shape)} (dp x tp x pp)")

    lm = PipelinedTransformerLM(vocab=64, width=16, n_heads=2,
                                n_layers=4, max_len=12, mesh=mesh,
                                remat=True)
    params = lm.shard_params(lm.init(jax.random.PRNGKey(0)))
    print("Wqkv sharding:",
          params["blocks"]["attn"]["Wqkv"].sharding.spec)

    rng = np.random.default_rng(0)
    toks = jax.device_put(jnp.asarray(rng.integers(0, 64, (8, 12))),
                          NamedSharding(mesh, P("data", None)))
    tgts = jax.device_put(jnp.asarray(rng.integers(0, 64, (8, 12))),
                          NamedSharding(mesh, P("data", None)))

    @jax.jit
    def train_step(p, toks, tgts):
        loss, g = jax.value_and_grad(lm.loss)(p, toks, tgts)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g), loss

    with mesh:
        ref = float(lm.loss(params, toks, tgts, pipelined=False))
        for step in range(5):
            params, loss = train_step(params, toks, tgts)
            print(f"step {step}: loss {float(loss):.4f}"
                  + (f"  (sequential golden {ref:.4f})" if step == 0
                     else ""))


if __name__ == "__main__":
    main()
