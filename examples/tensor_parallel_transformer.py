"""Megatron-paired tensor parallelism on a transformer classifier.

Run on any machine (virtual CPU mesh works):
    python examples/tensor_parallel_transformer.py

What it shows:
- a 2-block transformer stack built with the ordinary layer API,
- ParallelWrapper with ``.tensor_parallel()``: QKV sharded over heads,
  Wo + FFN as row/column pairs, class-sharded output — over a
  data x model mesh,
- the TP model's parameter shardings and a training run whose math is
  identical to the single-device model (see tests/test_tensor_parallel).
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

_bootstrap.pin_cpu_mesh(8)

import jax

_bootstrap.need_devices(8)

import numpy as np

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.attention import TransformerEncoderBlock
from deeplearning4j_tpu.nn.layers.feedforward import EmbeddingSequenceLayer
from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, create_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

VOCAB, WIDTH, T, CLASSES = 32, 16, 10, 4

conf = (NeuralNetConfiguration.Builder()
        .seed(7).updater(Adam(1e-2)).list()
        .layer(EmbeddingSequenceLayer(n_in=VOCAB, n_out=WIDTH))
        .layer(TransformerEncoderBlock(n_out=WIDTH, n_heads=4))
        .layer(TransformerEncoderBlock(n_out=WIDTH, n_heads=4))
        .layer(RnnOutputLayer(n_out=CLASSES))
        .set_input_type(InputType.recurrent(1, T))
        .build())
model = MultiLayerNetwork(conf).init()

mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
wrapper = (ParallelWrapper.builder(model)
           .mesh(mesh)
           .tensor_parallel()
           .build())

rng = np.random.default_rng(0)
feats = rng.integers(0, VOCAB, (64, T)).astype(np.float32)
labels = np.zeros((64, T, CLASSES), np.float32)
labels[np.arange(64)[:, None], np.arange(T)[None, :],
       (feats.astype(int) % CLASSES)] = 1.0   # learnable: class = token%4

wrapper.fit(ArrayDataSetIterator(DataSet(feats, labels), batch_size=64),
            epochs=_bootstrap.sized(30, 4))

print("loss:", float(model._last_loss))
wqkv = model.params["layer_1"]["attn"]["Wqkv"]
print("Wqkv sharding:", wqkv.sharding.spec)
acc = (np.asarray(model.output(feats)).argmax(-1)
       == feats.astype(int) % CLASSES).mean()
print("token accuracy:", acc)
# the smoke tier trains too few epochs to demand convergence
assert _bootstrap.smoke() or acc > 0.95
