"""Import a Keras .h5 model and fine-tune it with transfer learning
(the reference's KerasModelImport + TransferLearning workflow,
SURVEY §3.5).

Run: JAX_PLATFORMS=cpu python examples/keras_import_finetune.py
(requires keras to build the fixture; import itself needs only h5py)
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.modelimport import (
    import_keras_sequential_model_and_weights,
)


def main():
    import keras
    from keras import layers as L

    km = keras.Sequential([
        keras.Input((12,)),
        L.Dense(32, activation="relu", name="feat1"),
        L.Dense(16, activation="relu", name="feat2"),
        L.Dense(4, activation="softmax", name="head"),
    ])
    km.save("/tmp/pretrained.h5")

    model = import_keras_sequential_model_and_weights("/tmp/pretrained.h5")
    print(model.summary())

    x = np.random.default_rng(0).normal(size=(64, 12)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(model.output(x)),
        np.asarray(km.predict(x, verbose=0)), rtol=2e-4, atol=2e-5)
    print("imported model matches Keras")

    # fine-tune on new labels
    y = np.eye(4, dtype=np.float32)[
        np.random.default_rng(1).integers(0, 4, 64)]
    before = model.score(DataSet(x, y))
    for _ in range(_bootstrap.sized(30, 2)):
        model.fit(DataSet(x, y))
    print(f"fine-tune loss {before:.3f} -> {model.score(DataSet(x, y)):.3f}")


if __name__ == "__main__":
    main()
