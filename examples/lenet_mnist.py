"""LeNet on MNIST — the minimum end-to-end slice (BASELINE config 0).

Build a config with the builder API, fit, evaluate, save, restore.
Run: JAX_PLATFORMS=cpu python examples/lenet_mnist.py
(analog of the reference's MNIST tutorial notebooks, dl4j-examples/)
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.serialization import (
    restore_multi_layer_network,
    save_model,
)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.listeners import (
    PerformanceListener,
    ScoreIterationListener,
)
from deeplearning4j_tpu.optimize.updaters import Adam


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(123)
            .updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                    activation=Activation.RELU))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                    activation=Activation.RELU))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())

    model = MultiLayerNetwork(conf).init()
    print(model.summary())
    model.set_listeners(ScoreIterationListener(10),
                        PerformanceListener(frequency=10))

    train = MnistDataSetIterator(batch_size=128,
                                 subset=_bootstrap.sized(4096, 256))
    test = MnistDataSetIterator(batch_size=128,
                                subset=_bootstrap.sized(1024, 128),
                                train=False)
    model.fit(train, epochs=_bootstrap.sized(2, 1))

    ev = model.evaluate(test)
    print(ev.stats())

    save_model(model, "/tmp/lenet.zip", save_updater=True)
    restored = restore_multi_layer_network("/tmp/lenet.zip")
    batch = next(iter(test))
    np.testing.assert_allclose(np.asarray(model.output(batch.features)),
                               np.asarray(restored.output(batch.features)),
                               rtol=1e-6)
    print("save/restore round-trip OK")


if __name__ == "__main__":
    main()
