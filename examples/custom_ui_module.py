"""Custom dashboard module + i18n — plug your own routes into the
training UI via the UIModule SPI and serve it in another language
(reference: the Play UI's UIModule.java + I18NProvider).

Run: JAX_PLATFORMS=cpu python examples/custom_ui_module.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import json
import urllib.request

from deeplearning4j_tpu.ui.modules import Route, UIModule
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


class LossBudgetModule(UIModule):
    """A monitoring module: tracks whether training stays under a loss
    budget, updated live from the records the server receives."""

    def __init__(self, budget: float):
        self.budget = budget
        self.worst = None

    def get_routes(self):
        return [
            Route("GET", "/api/lossbudget",
                  lambda ctx, q, body: {
                      "budget": self.budget,
                      "worst_seen": self.worst,
                      "ok": self.worst is None
                      or self.worst <= self.budget}),
            Route("POST", "/api/lossbudget",
                  self._set_budget),
        ]

    def _set_budget(self, ctx, q, body):
        self.budget = float(body["budget"])
        return {"ok": True, "budget": self.budget}

    def on_update(self, record):          # every remote-routed record
        score = record.get("score")
        if score is not None:
            self.worst = (score if self.worst is None
                          else max(self.worst, score))


def main():
    mod = LossBudgetModule(budget=2.0)
    srv = (UIServer(port=0).attach(InMemoryStatsStorage())
           .register_module(mod).start())
    try:
        # feed a couple of records through the remote-receiver route
        for it, score in enumerate((1.2, 0.9, 2.6)):
            req = urllib.request.Request(
                srv.url + "/remote",
                data=json.dumps({"record": {
                    "session_id": "demo", "iteration": it,
                    "score": score}}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req).read()

        with urllib.request.urlopen(srv.url + "/api/lossbudget") as r:
            print("module state:", json.loads(r.read()))

        # raise the budget through the module's own POST route
        req = urllib.request.Request(
            srv.url + "/api/lossbudget",
            data=json.dumps({"budget": 3.0}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req).read()
        with urllib.request.urlopen(srv.url + "/api/lossbudget") as r:
            print("after raise: ", json.loads(r.read()))

        # the dashboard itself, served in Japanese
        with urllib.request.urlopen(srv.url + "/?lang=ja") as r:
            page = r.read().decode("utf-8")
        print("ja dashboard nav contains 概要:", "概要" in page)
    finally:
        srv.stop()


if __name__ == "__main__":
    main()
