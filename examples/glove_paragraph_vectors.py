"""GloVe + ParagraphVectors — co-occurrence embeddings and document
vectors on a topical toy corpus (the reference's GloVe /
ParagraphVectors tutorials, dl4j-examples/nlp).

Run: JAX_PLATFORMS=cpu python examples/glove_paragraph_vectors.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.nlp.sentence_iterators import LabelledDocument
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors


def corpus(n=300, seed=0):
    rng = np.random.default_rng(seed)
    topics = {
        "animals": ["cat", "dog", "bird", "fish", "horse", "fur",
                    "paw", "tail"],
        "vehicles": ["car", "truck", "train", "engine", "wheel",
                     "road", "fuel", "driver"],
    }
    sents, labels = [], []
    for _ in range(n):
        t = rng.choice(sorted(topics))
        sents.append(" ".join(rng.choice(topics[t], 10)))
        labels.append(t)
    return sents, labels


def main():
    sents, labels = corpus()

    # GloVe: AdaGrad over the weighted log-co-occurrence objective
    glove = Glove(layer_size=24, window_size=4, min_word_frequency=1,
                  epochs=_bootstrap.sized(20, 3),
                  learning_rate=0.05, seed=3)
    glove.fit(sents)
    print("glove: cat~dog", round(glove.similarity("cat", "dog"), 3),
          "vs cat~truck", round(glove.similarity("cat", "truck"), 3))
    print("glove nearest to 'engine':",
          glove.words_nearest("engine", top_n=3))

    # ParagraphVectors (DBOW): label vectors live in the same space
    docs = [LabelledDocument(content=s, labels=[f"doc_{i}"])
            for i, s in enumerate(sents[:100])]
    pv = ParagraphVectors(layer_size=24, window_size=4,
                          epochs=_bootstrap.sized(10, 2),
                          negative=4, min_word_frequency=1, seed=5)
    pv.fit(docs)
    # two animal docs should be closer than an animal/vehicle pair
    a = next(i for i, l in enumerate(labels[:100]) if l == "animals")
    b = next(i for i, l in enumerate(labels[:100])
             if l == "animals" and i != a)
    v = next(i for i, l in enumerate(labels[:100]) if l == "vehicles")
    same = pv.similarity(f"doc_{a}", f"doc_{b}")
    diff = pv.similarity(f"doc_{a}", f"doc_{v}")
    print(f"paragraph vectors: same-topic {same:.3f} "
          f"vs cross-topic {diff:.3f}")


if __name__ == "__main__":
    main()
