"""Pipeline-parallel causal LM with the circular/interleaved schedule.

    python examples/pipeline_parallel_lm.py

Four pipeline stages, each holding TWO interleaved transformer blocks
(Megatron "virtual pipeline"): an 8-layer LM trains with embed/unembed
outside the pipelined region and per-tick rematerialization.
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

_bootstrap.pin_cpu_mesh(8)

import jax

_bootstrap.need_devices(4)

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.pipeline import (
    PIPE_AXIS,
    PipelinedTransformerLM,
)

S = 4                       # pipeline stages (devices)
mesh = Mesh(np.array(jax.devices()[:S]), (PIPE_AXIS,))
lm = PipelinedTransformerLM(vocab=32, width=16, n_heads=4,
                            n_layers=2 * S, max_len=16, mesh=mesh,
                            remat=True)
params = lm.init(jax.random.PRNGKey(0))

rng = np.random.default_rng(0)
toks = jnp.asarray(rng.integers(0, 32, (16, 12)))
tgts = (toks + 1) % 32      # learnable: next token = token + 1


@jax.jit
def step(p):
    loss, g = jax.value_and_grad(lm.loss)(p, toks, tgts)
    return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g), loss


for i in range(_bootstrap.sized(70, 12)):
    params, loss = step(params)
    if i % 10 == 0:
        print(f"step {i:3d}  loss {float(loss):.4f}")
print(f"final loss {float(loss):.4f}")
# the smoke tier runs too few steps to demand convergence
assert _bootstrap.smoke() or float(loss) < 1.0

# sanity: the pipelined loss equals the sequential stack bit-for-bit
seq = float(lm.loss(params, toks, tgts, pipelined=False))
pipe = float(lm.loss(params, toks, tgts))
print(f"pipelined {pipe:.6f} == sequential {seq:.6f}")
assert abs(pipe - seq) < 1e-5
