"""Memory reports + listener-based profiling — the observability
toolkit: analytic per-layer memory estimates, XLA compiled-buffer
analysis, and the performance listeners that feed the dashboard
(reference: NetworkMemoryReport + PerformanceListener).

Run: JAX_PLATFORMS=cpu python examples/memory_and_profiling.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.datasets.dataset import (
    ArrayDataSetIterator,
    DataSet,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.memory import memory_report, xla_memory_analysis
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.optimize.listeners import (
    PerformanceListener,
    ScoreIterationListener,
)
from deeplearning4j_tpu.optimize.updaters import Adam


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater(Adam(1e-3)).list()
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                    activation=Activation.RELU))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=64, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.convolutional(16, 16, 1))
            .build())

    # analytic estimate BEFORE building anything (NetworkMemoryReport)
    rep = memory_report(conf)
    print(rep)

    model = MultiLayerNetwork(conf).init()

    # compiled truth: what XLA actually allocates for the train step
    xla = xla_memory_analysis(model, batch_size=64, train=True)
    print("XLA train-step buffer stats (bytes):",
          {k: f"{v:,}" for k, v in xla.items()})

    # listener-based profiling during fit (PerformanceListener analog)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 16, 16, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)]
    model.set_listeners(ScoreIterationListener(5),
                        PerformanceListener(5))
    model.fit(ArrayDataSetIterator(DataSet(x, y), batch_size=64),
              epochs=_bootstrap.sized(3, 1))
    print("done — per-iteration samples/sec + ETL ms were printed by "
          "PerformanceListener above")


if __name__ == "__main__":
    main()
