"""Evaluation deep-dive — the full metric suite (top-N, MCC, F-beta,
G-measure, false-alarm rate), ROC / precision-recall / calibration
curve exports, and feeding them to the dashboard's Evaluation tab
(reference: Evaluation.java + eval/curves/* + the UI's evaluation
charts).

Run: JAX_PLATFORMS=cpu python examples/evaluation_metrics_curves.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.evaluation.evaluation import (
    ROC,
    Evaluation,
    EvaluationCalibration,
)


def main():
    rng = np.random.default_rng(0)
    n, classes = 600, 5
    labels = rng.integers(0, classes, n)
    # a mediocre-on-purpose classifier: logits biased toward the truth
    logits = rng.normal(0, 1.0, (n, classes))
    logits[np.arange(n), labels] += 1.6
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)

    ev = Evaluation(num_classes=classes, top_n=2)
    ev.eval(labels, probs)
    print(f"accuracy        {ev.accuracy():.3f}")
    print(f"top-2 accuracy  {ev.top_n_accuracy():.3f}")
    print(f"macro F1        {ev.f1():.3f}   F2 {ev.f_beta(2.0):.3f}")
    print(f"G-measure       {ev.g_measure():.3f}")
    print(f"Matthews corr   {ev.matthews_correlation():.3f}")
    print(f"false alarm     {ev.false_alarm_rate():.3f}")
    print(ev.stats().splitlines()[-3])      # a per-class table row

    # binary ROC + PR curves: exact, tie-collapsed threshold points
    y_bin = (labels == 0).astype(float)
    roc = ROC()
    roc.eval(y_bin, probs[:, 0])
    curve = roc.get_roc_curve()
    pr = roc.get_precision_recall_curve()
    print(f"AUC {roc.calculate_auc():.3f} "
          f"({curve.num_points()} exact points), "
          f"AUPRC {roc.calculate_auprc():.3f}")
    t, p, r = pr.get_point_at_precision(0.5)
    print(f"first threshold with precision>=0.5: {t:.3f} (recall {r:.3f})")

    # calibration: reliability diagram + probability histogram
    cal = EvaluationCalibration(reliability_bins=10)
    onehot = np.eye(classes)[labels]
    cal.eval(onehot, probs)
    print(f"expected calibration error {cal.expected_calibration_error():.4f}")

    # everything above renders in the dashboard's Evaluation tab:
    #   srv = UIServer(port=9000).attach(InMemoryStatsStorage()).start()
    #   srv.upload_evaluation(roc=roc, calibration=cal)
    # (see examples/dashboard_training_ui.py for the server setup)


if __name__ == "__main__":
    main()
