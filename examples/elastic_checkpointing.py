"""Sharded checkpoints + elastic resharding — save from one mesh
layout, restore onto another (the pod-scale orbax-style flow:
every process writes only its shards; restore reads only the regions
the new layout needs).

Run: python examples/elastic_checkpointing.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import tempfile

_bootstrap.pin_cpu_mesh(8)

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import (
    ArrayDataSetIterator,
    DataSet,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel.checkpoint import (
    latest_checkpoint,
    restore_sharded,
    save_sharded,
)
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    create_mesh,
)


def model():
    conf = (NeuralNetConfiguration.Builder()
            .seed(3).updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=64))
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=4))
            .set_input_type(InputType.feed_forward(16))
            .build())
    return MultiLayerNetwork(conf).init()


def main():
    _bootstrap.need_devices(8)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 128)]

    m = model()
    m.fit(ArrayDataSetIterator(DataSet(x, y), batch_size=64),
          epochs=_bootstrap.sized(2, 1))
    out_before = np.asarray(m.output(x[:8]), np.float32)

    ckpt_dir = tempfile.mkdtemp(prefix="dl4j_ckpt_")
    path = save_sharded(m.train_state, ckpt_dir)
    print("saved:", path)

    # restore onto an 8-device data x model mesh: params placed with
    # the new layout directly (no full-array host materialization)
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2}, jax.devices()[:8])
    m2 = model()
    restore_sharded(m2, latest_checkpoint(ckpt_dir), mesh=mesh)
    out_after = np.asarray(m2.output(x[:8]), np.float32)
    np.testing.assert_allclose(out_after, out_before, rtol=1e-5,
                               atol=1e-6)
    print("restored onto", dict(mesh.shape),
          "- outputs identical, training resumes at iteration",
          int(m2.train_state.iteration))
    m2.fit(ArrayDataSetIterator(DataSet(x, y), batch_size=64), epochs=1)
    print("resumed fine; final loss", m2.score())


if __name__ == "__main__":
    main()
