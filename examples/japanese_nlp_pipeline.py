"""Japanese NLP pipeline — morphological analysis (POS + readings) and
word vectors over the CJK language pack (reference:
deeplearning4j-nlp-japanese's Kuromoji tokenizer feeding Word2Vec).

Run: JAX_PLATFORMS=cpu python examples/japanese_nlp_pipeline.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.nlp.language_packs import (
    ChineseTokenizerFactory,
    JapaneseTokenizerFactory,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


def main():
    ja = JapaneseTokenizerFactory()

    # Kuromoji Token analog: surface + coarse ipadic POS + reading
    print("-- morphological analysis --")
    for t in ja.analyze("東京で日本語を勉強する。"):
        print(f"  {t.surface}\t{t.part_of_speech}"
              f"\t{t.reading or '-'}")

    # the same factory drives Word2Vec (TokenizerFactory contract)
    rng = np.random.default_rng(0)
    sentences = [
        "学生は学校で勉強する",       # school theme
        "先生は学校で仕事をする",
        "学生は学校に行く",
        "会社で仕事をする",           # work theme
        "電車で会社に行く",
        "会社の仕事は大変",
    ]
    corpus = [sentences[i] for i in rng.integers(
        0, len(sentences), _bootstrap.sized(400, 60))]
    w2v = Word2Vec(tokenizer_factory=ja, layer_size=16, window_size=3,
                   min_word_frequency=2, epochs=_bootstrap.sized(8, 2),
                   negative=4, seed=1)
    w2v.fit(corpus)
    print("-- embeddings --")
    print("  学校 ~ 学生:", round(w2v.similarity("学校", "学生"), 3),
          " vs 学校 ~ 電車:", round(w2v.similarity("学校", "電車"), 3))
    print("  nearest to 会社:", w2v.words_nearest("会社", top_n=3))

    # Chinese unigram-DP segmenter from the same pack
    zh = ChineseTokenizerFactory()
    print("-- chinese segmentation --")
    print(" ", "/".join(
        zh.create("我们在学习机器学习和自然语言处理").get_tokens()))


if __name__ == "__main__":
    main()
