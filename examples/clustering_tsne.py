"""Clustering + t-SNE — KMeans over feature vectors, VPTree
nearest-neighbor lookup, and a Barnes-Hut t-SNE projection (the
workflow the reference's deeplearning4j-nearestneighbors +
dl4j-examples t-SNE tutorial covers).

Run: JAX_PLATFORMS=cpu python examples/clustering_tsne.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.manifold.tsne import BarnesHutTsne


def main():
    rng = np.random.default_rng(0)
    # three well-separated gaussian blobs in 16-D
    centers = rng.normal(0, 6.0, (3, 16))
    labels = rng.integers(0, 3, 300)
    x = (centers[labels] + rng.normal(0, 1.0, (300, 16))) \
        .astype(np.float32)

    # KMeans (reference API: KMeansClustering.setup(...).applyTo(points))
    km = KMeansClustering.setup(n_clusters=3, max_iterations=50)
    km.apply_to(x)
    assign = km.predict(x)
    # cluster purity vs the generating labels
    purity = np.mean([
        np.bincount(labels[assign == c]).max()
        for c in range(3)]) / np.mean(np.bincount(assign))
    print(f"kmeans: 3 clusters, purity ~{purity:.2f}")

    # VPTree nearest neighbors: points in the same blob come back first
    tree = VPTree(x)
    idx, dists = tree.search(x[0], k=5)
    print("5-NN of point 0 share its cluster:",
          bool(np.all(labels[idx] == labels[0])))

    # Barnes-Hut t-SNE down to 2-D (feed the coords to
    # UIServer.upload_tsne to see them in the dashboard's t-SNE tab)
    coords = BarnesHutTsne(perplexity=20.0,
                           n_iter=_bootstrap.sized(250, 30),
                           seed=1).fit_transform(x)
    # blobs stay separated in the embedding: mean within-cluster
    # distance << mean between-cluster distance
    within = np.mean([np.std(coords[labels == c], axis=0).mean()
                      for c in range(3)])
    between = np.std(coords, axis=0).mean()
    print(f"t-SNE 2-D embedding: within-cluster spread {within:.2f} "
          f"vs overall {between:.2f}")


if __name__ == "__main__":
    main()
