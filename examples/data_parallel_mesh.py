"""Single-host data-parallel training over a device mesh — the
ParallelWrapper workflow (SURVEY §3.3) the TPU way: mesh + sharded step.

Run: python examples/data_parallel_mesh.py   (8 virtual CPU devices)
On a real TPU host, JAX_PLATFORMS=tpu uses all local chips instead.
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

_bootstrap.pin_cpu_mesh(8)

import jax  # noqa: E402

_bootstrap.need_devices(2)

from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator  # noqa: E402
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.inputs import InputType  # noqa: E402
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer  # noqa: E402
from deeplearning4j_tpu.nn.layers.output import OutputLayer  # noqa: E402
from deeplearning4j_tpu.ops.activations import Activation  # noqa: E402
from deeplearning4j_tpu.ops.losses import LossFunction  # noqa: E402
from deeplearning4j_tpu.optimize.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.parallel.wrapper import (  # noqa: E402
    ParallelWrapper,
    TrainingMode,
)


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=256, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(784))
            .build())
    model = MultiLayerNetwork(conf).init()

    # SHARED_GRADIENTS == per-step allreduce over the mesh's data axis;
    # AVERAGING == local SGD with periodic parameter averaging
    pw = (ParallelWrapper.Builder(model)
          .workers(len(jax.devices()))
          .training_mode(TrainingMode.SHARED_GRADIENTS)
          .build())
    train = MnistDataSetIterator(batch_size=256,
                                 subset=_bootstrap.sized(4096, 512))
    pw.fit(train, epochs=_bootstrap.sized(2, 1))

    test = MnistDataSetIterator(batch_size=256, subset=1024, train=False)
    print("accuracy:", model.evaluate(test).accuracy())


if __name__ == "__main__":
    main()
