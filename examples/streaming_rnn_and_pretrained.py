"""Round-4 features end to end: pretrained zoo restore + graph-model
streaming RNN inference + TBPTT training.

1. Restore the committed LeNet weights (`ZooModel.init_pretrained` —
   the reference's download+checksum contract, served from package
   resources in this zero-egress build) and classify real digits.
2. Build a recurrent ComputationGraph, train it with truncated BPTT
   (`GraphBuilder.backprop_type("tbptt")`), then stream inference one
   timestep at a time with stored state (`rnn_time_step` — reference:
   ComputationGraph.rnnTimeStep).

Run: JAX_PLATFORMS=cpu python examples/streaming_rnn_and_pretrained.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.zoo.models import LeNet


def pretrained_lenet():
    model = LeNet().init_pretrained(flavor="digits")
    ev = model.evaluate(DigitsDataSetIterator(batch_size=64, train=False,
                                              shuffle=False))
    print(f"pretrained LeNet on held-out real digits: "
          f"accuracy {ev.accuracy():.4f}")


def streaming_rnn():
    f, h, c = 3, 16, 2
    g = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(5e-3))
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(f)))
    g.add_layer("lstm", LSTM(n_out=h, activation=Activation.TANH), "in")
    g.add_layer("out", RnnOutputLayer(n_out=c, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX),
                "lstm")
    g.set_outputs("out")
    g.backprop_type("tbptt").tbptt_fwd_length(8)
    net = ComputationGraph(g.build()).init()

    # toy task: does the running mean of feature 0 exceed 0?
    rng = np.random.default_rng(0)
    n, t = 64, 24
    x = rng.normal(0, 1, (n, t, f)).astype(np.float32)
    run_mean = np.cumsum(x[..., 0], axis=1) / np.arange(1, t + 1)
    y = np.zeros((n, t, c), np.float32)
    y[..., 1] = (run_mean > 0)
    y[..., 0] = 1.0 - y[..., 1]
    ds = DataSet(x, y)
    for epoch in range(_bootstrap.sized(30, 4)):
        net.fit(ds)               # chunks of 8 timesteps under the hood
    print(f"TBPTT-trained graph score: {float(net.score(ds)):.4f}")

    # stream one step at a time; state carries across calls
    net.rnn_clear_previous_state()
    streamed = np.stack([np.asarray(net.rnn_time_step(x[:, ti]))
                         for ti in range(t)], axis=1)
    full = np.asarray(net.output(x))
    drift = float(np.abs(streamed - full).max())
    print(f"streamed-vs-full forward max drift: {drift:.2e}")
    acc = float(((streamed[..., 1] > 0.5) == (y[..., 1] > 0.5)).mean())
    print(f"streaming accuracy on the toy task: {acc:.3f}")


if __name__ == "__main__":
    pretrained_lenet()
    streaming_rnn()
