"""Early stopping + transfer learning — train with a validation-driven
stop, then reuse the trunk on a new task (freeze + head replacement +
bf16 fine-tune).

Run: JAX_PLATFORMS=cpu python examples/early_stopping_transfer.py
(analog of the reference's EarlyStoppingMNIST + TransferLearning
tutorials, dl4j-examples/)
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import numpy as np

from deeplearning4j_tpu.datasets.dataset import (
    ArrayDataSetIterator,
    DataSet,
)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochsTerminationCondition,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    TransferLearning,
)
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd


def blobs(rng, n, n_classes, dim=12, spread=2.5):
    centers = rng.normal(0, spread, (n_classes, dim))
    yi = rng.integers(0, n_classes, n)
    x = centers[yi] + rng.normal(0, 1.0, (n, dim))
    y = np.eye(n_classes, dtype=np.float32)[yi]
    return x.astype(np.float32), y


def main():
    rng = np.random.default_rng(7)
    x, y = blobs(rng, 512, 4)
    xv, yv = blobs(rng, 128, 4)

    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(5e-3)).list()
            .layer(DenseLayer(n_out=32, activation=Activation.RELU))
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=4))
            .set_input_type(InputType.feed_forward(12))
            .build())

    # early stopping: stop when validation loss stalls for 3 epochs
    esc = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(
               MaxEpochsTerminationCondition(_bootstrap.sized(30, 5)),
               ScoreImprovementEpochsTerminationCondition(3))
           .score_calculator(DataSetLossCalculator(
               ArrayDataSetIterator(DataSet(xv, yv), batch_size=64)))
           .evaluate_every_n_epochs(1)
           .build())
    result = EarlyStoppingTrainer(
        esc, MultiLayerNetwork(conf),
        ArrayDataSetIterator(DataSet(x, y), batch_size=64)).fit()
    print(f"stopped: {result.termination_reason} "
          f"(best epoch {result.best_model_epoch}, "
          f"score {result.best_model_score:.4f})")
    base = result.best_model

    # transfer: freeze the trunk, swap the 4-way head for 3 classes,
    # fine-tune at bf16 compute (the TPU recipe)
    x3, y3 = blobs(rng, 256, 3)
    ft = (TransferLearning.Builder(base)
          .fine_tune_configuration(
              FineTuneConfiguration.Builder().updater(Sgd(5e-2))
              .compute_dtype("bfloat16").build())
          .set_feature_extractor(1)          # freeze layers 0..1
          .n_out_replace(2, 3)               # new 3-class head
          .build())
    ft.fit(ArrayDataSetIterator(DataSet(x3, y3), batch_size=64),
           epochs=_bootstrap.sized(40, 6))
    ev = ft.evaluate(ArrayDataSetIterator(DataSet(x3, y3), batch_size=64))
    print(f"fine-tuned accuracy on the new task: {ev.accuracy():.3f}")
    w0 = np.asarray(base.train_state.params["layer_0"]["W"])
    w0_ft = np.asarray(ft.train_state.params["layer_0"]["W"])
    print("frozen trunk untouched:", bool(np.array_equal(w0, w0_ft)))


if __name__ == "__main__":
    main()
