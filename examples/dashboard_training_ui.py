"""Training dashboard: overview/model/system/activations/t-SNE tabs.

    JAX_PLATFORMS=cpu python examples/dashboard_training_ui.py

Trains a small conv net on real handwritten digits while serving the
dashboard; open the printed URL, then Ctrl-C to stop.
"""

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
from deeplearning4j_tpu.manifold.tsne import Tsne
from deeplearning4j_tpu.zoo.models import LeNet
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, UIServer
from deeplearning4j_tpu.ui.convolutional import ConvolutionalListener

storage = InMemoryStatsStorage()
server = UIServer.get_instance(port=9000).attach(storage).start()
print("dashboard:", server.url)

model = LeNet(compute_dtype="float32").init()
train_it = DigitsDataSetIterator(batch_size=64, train=True)
example = next(iter(train_it)).features
model.set_listeners(
    StatsListener(storage, session_id="digits"),
    ConvolutionalListener(storage, session_id="digits",
                          frequency=5).set_example(example))
train_it.reset()
model.fit(train_it, epochs=10)

acc = model.evaluate(DigitsDataSetIterator(batch_size=64, train=False,
                                           shuffle=False)).accuracy()
print("test accuracy:", acc)

# populate the t-SNE tab with the test set's penultimate activations
imgs, labels = DigitsDataSetIterator.fetch(train=False)
acts = np.asarray(model.feed_forward(imgs[:300])[-2])
coords = Tsne(n_components=2, perplexity=20, n_iter=300).fit_transform(
    acts.reshape(acts.shape[0], -1))
server.upload_tsne(coords, labels[:300].tolist())
print("t-SNE uploaded — press Ctrl-C to exit")
try:
    import time
    time.sleep(3600)
except KeyboardInterrupt:
    pass
server.stop()
