"""Training dashboard: overview/model/system/activations/t-SNE tabs,
all self-populating (no manual uploads).

    JAX_PLATFORMS=cpu python examples/dashboard_training_ui.py

Trains a small conv net on real handwritten digits while serving the
dashboard; open the printed URL — the Model tab supports per-layer
drill-down (click a node), the Activations tab has an iteration slider
over the full recorded history, and the t-SNE tab refreshes itself from
the live model's penultimate activations. Ctrl-C to stop.
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)

import jax

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
from deeplearning4j_tpu.zoo.models import LeNet
from deeplearning4j_tpu.ui import (
    InMemoryStatsStorage,
    StatsListener,
    TsneListener,
    UIServer,
)
from deeplearning4j_tpu.ui.convolutional import ConvolutionalListener

storage = InMemoryStatsStorage()
# smoke tier: ephemeral port so parallel test runs never collide
server = UIServer.get_instance(
    port=_bootstrap.sized(9000, 0)).attach(storage).start()
print("dashboard:", server.url)

model = LeNet(compute_dtype="float32").init()
train_it = DigitsDataSetIterator(batch_size=64, train=True)
example = next(iter(train_it)).features
test_imgs, test_labels = DigitsDataSetIterator.fetch(train=False)
model.set_listeners(
    StatsListener(storage, session_id="digits"),
    ConvolutionalListener(storage, session_id="digits",
                          frequency=5).set_example(example),
    # the t-SNE tab populates itself from the live model every 20 steps
    TsneListener(server, frequency=20,
                 n_iter=_bootstrap.sized(250, 20)).set_example(
        test_imgs[:300], test_labels[:300]))
train_it.reset()
model.fit(train_it, epochs=_bootstrap.sized(10, 1))

acc = model.evaluate(DigitsDataSetIterator(batch_size=64, train=False,
                                           shuffle=False)).accuracy()
print("test accuracy:", acc)
if _bootstrap.smoke():
    print("smoke mode: exiting without the interactive wait")
else:
    print("dashboard live — press Ctrl-C to exit")
    try:
        import time
        time.sleep(3600)
    except KeyboardInterrupt:
        pass
server.stop()
