"""Measure the cost of compiled-in telemetry on the train step.

The observe/ design claim is "zero extra syncs steady-state": the
metric rows (loss, grad norm, update ratios, non-finite counts) are
computed inside the already-dispatched step and land in an on-device
ring buffer, so the only added cost is the device-side arithmetic and
one fetch every ``flush_interval`` steps. This benchmark times the same
model fit()ting the same batches with telemetry off and on
(flush_interval=50) and reports the overhead; --assert-overhead fails
the run when the median regression exceeds the tolerance (used as a
perf gate on the tier-1 CPU path).

Usage:
    python benchmarks/telemetry_overhead.py
    python benchmarks/telemetry_overhead.py --steps 300 \
        --assert-overhead --tolerance 0.02
"""

from __future__ import annotations

import argparse
import statistics
import time

import numpy as np


def build_model(seed: int = 7):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=512))
            .layer(DenseLayer(n_out=512))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(256)).build())
    return MultiLayerNetwork(conf).init()


def make_batches(n: int, batch: int = 512):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(0)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, 256)).astype(np.float32)
        idx = rng.integers(0, 10, batch)
        y = np.zeros((batch, 10), np.float32)
        y[np.arange(batch), idx] = 1.0
        out.append(DataSet(x, y))
    return out


def time_interleaved(model_a, model_b, batches, warmup: int = 20,
                     block: int = 10):
    """Median per-step wall time for both arms, measured in alternating
    blocks so machine-load drift hits both equally (sequential A-then-B
    runs showed ~20% run-to-run drift on a shared box — far above the
    effect being measured)."""
    for b in batches[:warmup]:
        model_a.fit(b)
        model_b.fit(b)
    t_a, t_b = [], []
    work = batches[warmup:]
    for i in range(0, len(work), block):
        chunk = work[i:i + block]
        for model, sink in ((model_a, t_a), (model_b, t_b)):
            for b in chunk:
                t0 = time.perf_counter()
                model.fit(b)
                sink.append(time.perf_counter() - t0)
    return statistics.median(t_a), statistics.median(t_b)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=300,
                    help="timed steps per arm (plus warmup)")
    ap.add_argument("--flush-interval", type=int, default=50)
    ap.add_argument("--with-histograms", action="store_true",
                    help="also compile in-step param/grad/update "
                         "histograms (flight-recorder config) — still "
                         "one fetch per flush interval")
    ap.add_argument("--hist-interval", type=int, default=10,
                    help="steps between in-step histogram snapshots "
                         "(with --with-histograms)")
    ap.add_argument("--assert-overhead", action="store_true",
                    help="exit 1 when overhead exceeds --tolerance")
    ap.add_argument("--tolerance", type=float, default=0.02,
                    help="max allowed fractional overhead (default 2%%)")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.observe import TelemetryCollector

    warmup = 20
    batches = make_batches(args.steps + warmup)

    base = build_model()
    mon = build_model()
    tel = TelemetryCollector(flush_interval=args.flush_interval,
                             histograms=args.with_histograms,
                             hist_interval=args.hist_interval)
    mon.set_telemetry(tel)
    t_off, t_on = time_interleaved(base, mon, batches, warmup)

    overhead = (t_on - t_off) / t_off
    mode = ("telemetry+histograms" if args.with_histograms
            else "telemetry")
    print(f"telemetry off: {t_off * 1e3:8.3f} ms/step (median of "
          f"{args.steps})")
    print(f"{mode} on: {t_on * 1e3:8.3f} ms/step "
          f"(flush every {args.flush_interval}, "
          f"{tel.fetch_count} device fetches)")
    print(f"overhead:      {overhead * 100:+.2f}%")

    if args.assert_overhead and overhead > args.tolerance:
        print(f"FAIL: overhead {overhead * 100:.2f}% exceeds the "
              f"{args.tolerance * 100:.1f}% budget")
        return 1
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
