"""ResNet50 train-step decomposition on the real chip (VERDICT weak#2).

Times the full train step and its pieces separately (forcing a host
transfer after each timing block — block_until_ready alone no-ops through
tunneled-device transports), pulls XLA's compiled cost analysis (FLOPs /
bytes) for each executable, and prints a roofline table: where the gap
between the measured matmul roofline and the model step goes.
PERF_ANALYSIS.md records the conclusions.

Run: python benchmarks/profile_resnet50.py [batch]
"""

import sys
import time

import numpy as np


def timed_scalar(fn, *args, n=20, warmup=3):
    """fn must return a scalar-ish array; host-fetch syncs the stream."""
    for _ in range(warmup):
        out = fn(*args)
    float(np.asarray(out).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    float(np.asarray(out).ravel()[0])
    return (time.perf_counter() - t0) / n


def cost(compiled):
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca.get("flops", 0)), float(ca.get("bytes accessed", 0))
    except Exception:
        return 0.0, 0.0


def main():
    import jax
    import jax.numpy as jnp
    import jax.random as jrandom

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    from deeplearning4j_tpu.optimize.updaters import Nesterovs
    from deeplearning4j_tpu.zoo.models import ResNet50

    model = ResNet50(num_classes=200, height=64, width=64, channels=3,
                     compute_dtype="bfloat16",
                     updater=Nesterovs(1e-2, 0.9)).init()
    ts = model.train_state

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3)).astype(np.float32))
    idx = rng.integers(0, 200, batch)
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), idx] = 1.0
    y = jnp.asarray(y)
    key = jrandom.PRNGKey(0)

    # ---- matmul roofline on this chip ------------------------------------
    m = 8192
    a = jnp.asarray(rng.normal(size=(m, m)), jnp.bfloat16)
    b = jnp.asarray(rng.normal(size=(m, m)), jnp.bfloat16)
    jmm = jax.jit(lambda a, b: jnp.sum((a @ b).astype(jnp.float32)))
    t_mm = timed_scalar(jmm, a, b, n=50)
    mm_tflops = 2 * m ** 3 / t_mm / 1e12

    # ---- piece 1: forward loss only --------------------------------------
    def fwd(params, mstate, x, y, key):
        loss, _ = model._loss(params, mstate, (x,), (y,), None, None, key,
                              ts.iteration)
        return loss

    jfwd = jax.jit(fwd)
    c_fwd = jfwd.lower(ts.params, ts.model_state, x, y, key).compile()
    t_fwd = timed_scalar(jfwd, ts.params, ts.model_state, x, y, key)

    # ---- piece 2: forward + backward (scalar probe on one grad leaf) -----
    def fwd_bwd(params, mstate, x, y, key):
        g = jax.grad(lambda p: fwd(p, mstate, x, y, key))(params)
        # touch every leaf so nothing is DCE'd, return a scalar
        return sum(jnp.sum(l.astype(jnp.float32) ** 2)
                   for l in jax.tree_util.tree_leaves(g))

    jfb = jax.jit(fwd_bwd)
    c_fb = jfb.lower(ts.params, ts.model_state, x, y, key).compile()
    t_fb = timed_scalar(jfb, ts.params, ts.model_state, x, y, key)

    # ---- piece 3: full train step (fwd+bwd+optimizer, donated) -----------
    step = model._build_train_step()
    n_steps, warm = 20, 3
    for i in range(warm):
        ts, loss = step(ts, (x,), (y,), None, None, jrandom.fold_in(key, i))
    float(loss)
    t0 = time.perf_counter()
    for i in range(n_steps):
        ts, loss = step(ts, (x,), (y,), None, None,
                        jrandom.fold_in(key, warm + i))
    float(loss)
    t_step = (time.perf_counter() - t0) / n_steps

    f_fwd, by_fwd = cost(c_fwd)
    f_fb, by_fb = cost(c_fb)

    print(f"batch={batch}")
    print(f"matmul roofline: {mm_tflops:.1f} TFLOP/s "
          f"({t_mm * 1e3:.2f} ms for {m}x{m}x{m})")
    for name, t, fl, by in (("fwd", t_fwd, f_fwd, by_fwd),
                            ("fwd+bwd", t_fb, f_fb, by_fb)):
        tf = fl / t / 1e12 if fl else 0
        gbs = by / t / 1e9 if by else 0
        print(f"{name:8s}: {t * 1e3:7.2f} ms  {fl / 1e9:8.1f} GFLOP  "
              f"{tf:6.1f} TFLOP/s  {by / 1e6:8.0f} MB  {gbs:7.0f} GB/s")
    print(f"step    : {t_step * 1e3:7.2f} ms  "
          f"({batch / t_step:,.0f} img/s)")
    print(f"optimizer+cast overhead vs fwd+bwd: "
          f"{(t_step - t_fb) * 1e3:.2f} ms")


if __name__ == "__main__":
    main()
