"""Elastic training under a straggler: ASYNC_ELASTIC vs SYNC rounds.

The claim under test (parallel/wrapper.py ASYNC_ELASTIC): a synchronous
averaging round is hostage to its slowest worker — every round pays the
full straggler delay at the barrier. The bounded-staleness elastic mode
drops the late worker from that round's average (merging its
contribution staleness-weighted when it rejoins), so the round rate is
set by the HEALTHY workers and the straggler costs ~nothing.

Three gates (the --smoke CI contract):

- **throughput**: with one worker stalling ``--delay-ms`` every round,
  ASYNC_ELASTIC sustains >= 1.5x the SYNC round rate. The SYNC arm
  simulates the barrier stall with a per-round sleep listener (single
  host: the wrapper's workers are mesh shards, so the stall IS the
  barrier cost a real straggler would impose); the ASYNC arm routes the
  same straggler through ``ElasticOptions.straggler_policy`` — past the
  round deadline, dropped, no stall.
- **quality**: the straggler arm's replica divergence stays under the
  hard-sync threshold (the run is not silently diverging to garbage).
- **equivalence**: with NO straggler, ASYNC_ELASTIC converges to the
  same loss as plain AVERAGING (rel 1e-3) — the delta merge collapses
  to parameter averaging when everyone is present.

Arms alternate per trial (A/B interleaved, like input_pipeline.py) so
machine-load drift hits both equally.

Usage:
    python -m benchmarks.elastic                  # timed A/B, 3 trials
    python -m benchmarks.elastic --smoke          # CI gate, < ~60 s
    python -m benchmarks.elastic --delay-ms 200   # heavier straggler
"""

from __future__ import annotations

import argparse
import json
import os
import time

# the A/B needs 4 mesh-shard workers; on a plain CPU host that means
# the same virtual 8-device mesh tests/conftest.py forces (must be set
# before the first jax import in the deferred builders below)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

import numpy as np


def _conf(seed=1, lr=0.05):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.optimize.updaters import Sgd
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())


def _iterator(batch=32):
    from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator
    return IrisDataSetIterator(batch_size=batch)


def _build(mode, workers, k, opts=None, model=None):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    model = model or MultiLayerNetwork(_conf()).init()
    b = (ParallelWrapper.builder(model).training_mode(mode)
         .workers(workers).averaging_frequency(k))
    if opts is not None:
        b = b.elastic_options(opts)
    return model, b.build()


def _run_sync_arm(epochs, workers, k, delay_ms):
    """SYNC baseline: AVERAGING rounds + a listener that sleeps the
    straggler delay once per round — the barrier waiting on the slow
    worker. Returns (rounds, wall_s, loss)."""
    from deeplearning4j_tpu.optimize.listeners import TrainingListener
    from deeplearning4j_tpu.parallel.wrapper import TrainingMode

    class _BarrierStall(TrainingListener):
        def iteration_done(self, m, iteration, epoch, loss, etl, n):
            if delay_ms > 0:
                time.sleep(delay_ms / 1e3)

    model, w = _build(TrainingMode.AVERAGING, workers, k)
    if delay_ms > 0:
        model.add_listeners(_BarrierStall())
    t0 = time.perf_counter()
    w.fit(_iterator(), epochs=epochs)
    wall = time.perf_counter() - t0
    steps = int(model.train_state.iteration)  # host-sync-ok: once per arm, after fit
    return steps // k, wall, float(model._last_loss)  # host-sync-ok: once per arm, after fit


def _run_async_arm(epochs, workers, k, delay_ms):
    """ASYNC_ELASTIC arm: worker 1 reports ``delay_ms`` late every
    round via the straggler policy — past the deadline it is dropped,
    the healthy workers' round never stalls. Returns
    (rounds, wall_s, loss, divergence, threshold)."""
    from deeplearning4j_tpu.observe.registry import default_registry
    from deeplearning4j_tpu.parallel.wrapper import (
        ElasticOptions, TrainingMode)

    def policy(rnd, n):
        d = [0.0] * n
        if delay_ms > 0:
            d[1] = float(delay_ms)  # host-sync-ok: python config scalar
        return d

    opts = ElasticOptions(round_deadline_ms=min(50.0, delay_ms or 50.0),
                          straggler_policy=policy)
    model, w = _build(TrainingMode.ASYNC_ELASTIC, workers, k, opts=opts)
    t0 = time.perf_counter()
    w.fit(_iterator(), epochs=epochs)
    wall = time.perf_counter() - t0
    steps = int(model.train_state.iteration)  # host-sync-ok: once per arm, after fit
    div = default_registry().gauge("dl4j_replica_divergence").get(
        session="elastic")
    return (steps // k, wall, float(model._last_loss),  # host-sync-ok: once per arm, after fit
            div, opts.divergence_threshold)


def _equivalence(epochs, workers, k):
    """No straggler: ASYNC_ELASTIC must converge to AVERAGING's loss."""
    from deeplearning4j_tpu.parallel.wrapper import (
        ElasticOptions, TrainingMode)
    ma, wa = _build(TrainingMode.AVERAGING, workers, k)
    wa.fit(_iterator(), epochs=epochs)
    me, we = _build(TrainingMode.ASYNC_ELASTIC, workers, k,
                    opts=ElasticOptions())
    we.fit(_iterator(), epochs=epochs)
    la = float(ma._last_loss)  # host-sync-ok: once per arm, after fit
    le = float(me._last_loss)  # host-sync-ok: once per arm, after fit
    return la, le


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: short run, assert all three gates")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--trials", type=int, default=None)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--delay-ms", type=float, default=2000.0,
                    help="straggler stall per round. The throughput "
                    "claim is about straggler-DOMINATED rounds (a real "
                    "straggler stalls seconds, not the CPU arm's "
                    "~0.5-1 s of compute); shrink this to explore the "
                    "compute-bound crossover instead")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    epochs = args.epochs or (3 if args.smoke else 10)
    trials = args.trials or (1 if args.smoke else 3)

    # warmup: compile both arms' steps outside the timed region
    _run_sync_arm(1, args.workers, args.k, 0.0)
    _run_async_arm(1, args.workers, args.k, 0.0)

    sync_rates, async_rates, divs, thr = [], [], [], None
    for _ in range(trials):   # interleaved A/B
        r_s, t_s, _ = _run_sync_arm(epochs, args.workers, args.k,
                                    args.delay_ms)
        r_a, t_a, _, div, thr = _run_async_arm(
            epochs, args.workers, args.k, args.delay_ms)
        sync_rates.append(r_s / t_s)
        async_rates.append(r_a / t_a)
        if div is not None:
            divs.append(div)

    sync_rate = float(np.median(sync_rates))  # host-sync-ok: host timing stats
    async_rate = float(np.median(async_rates))  # host-sync-ok: host timing stats
    ratio = async_rate / sync_rate
    max_div = max(divs) if divs else float("nan")  # host-sync-ok: host gauge values

    loss_avg, loss_async = _equivalence(epochs, args.workers, args.k)
    loss_rel = abs(loss_async - loss_avg) / max(abs(loss_avg), 1e-12)

    out = {"sync_rounds_per_s": sync_rate,
           "async_rounds_per_s": async_rate,
           "ratio": ratio,
           "delay_ms": args.delay_ms,
           "divergence": max_div,
           "divergence_threshold": thr,
           "loss_averaging": loss_avg,
           "loss_async_elastic": loss_async,
           "loss_rel_err": loss_rel}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"SYNC  (straggler stalls barrier): "
              f"{sync_rate:7.2f} rounds/s")
        print(f"ASYNC (straggler dropped):        "
              f"{async_rate:7.2f} rounds/s   ratio {ratio:.2f}x")
        print(f"divergence {max_div:.3g} (threshold {thr:g})")
        print(f"loss: AVERAGING {loss_avg:.6f}  ASYNC_ELASTIC "
              f"{loss_async:.6f}  rel {loss_rel:.2e}")

    assert ratio >= 1.5, (
        f"ASYNC_ELASTIC only {ratio:.2f}x SYNC round rate (need 1.5x)")
    assert not divs or max_div < thr, (
        f"divergence {max_div:.3g} >= threshold {thr:g}")
    assert loss_rel < 1e-3, (
        f"straggler-free ASYNC_ELASTIC loss {loss_async} != "
        f"AVERAGING {loss_avg} (rel {loss_rel:.2e})")
    print("elastic gates: OK")
    return out


if __name__ == "__main__":
    main()
