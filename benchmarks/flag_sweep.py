"""Batch-size sweep of the scanned ResNet50 step (each variant runs in a
fresh process so backend state never leaks between runs).

The libtpu in this image rejects the latency-hiding-scheduler /
scoped-vmem XLA flags (PERF_ANALYSIS.md), so the sweep dimension is the
batch size; K is scaled to keep work-per-dispatch roughly constant.
"""

import json
import subprocess
import sys
import time

import numpy as np

VARIANTS = {
    "b64": (64, 256),
    "b128": (128, 128),
    "b256": (256, 64),
    "b384": (384, 42),
    "b512": (512, 32),
    "b1024": (1024, 16),
    "b2048": (2048, 8),
}


def run_one(name):
    batch, k = VARIANTS[name]
    import jax.numpy as jnp
    import jax.random as jrandom
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Nesterovs
    from deeplearning4j_tpu.zoo.models import ResNet50

    model = ResNet50(num_classes=200, height=64, width=64, channels=3,
                     compute_dtype="bfloat16",
                     updater=Nesterovs(1e-2, 0.9)).init()

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
        return model._loss(params, mstate, (feats,), (labels,), fmask,
                           lmask, rng, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3)).astype(np.float32))
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), rng.integers(0, 200, batch)] = 1.0
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (k, batch, 200))
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    float(np.asarray(losses[-1]))
    n = 3
    t0 = time.perf_counter()
    for i in range(n):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, i))
    float(np.asarray(losses[-1]))
    dt = time.perf_counter() - t0
    print(json.dumps({"variant": name, "batch": batch, "k": k,
                      "img_per_sec": round(n * k * batch / dt, 1)}))


if __name__ == "__main__":
    if len(sys.argv) > 1:
        run_one(sys.argv[1])
    else:
        for name in VARIANTS:
            subprocess.run([sys.executable, __file__, name], timeout=560)
