"""Measure the flash-vs-XLA attention crossover on the real chip.

The helper-SPI dispatcher (ops/pallas_kernels.attention) should pick the
plain XLA lowering at short sequence lengths — the full score matrix is
cheap there and XLA fuses it into large batched MXU matmuls — and the
streaming Pallas kernel at long lengths where the O(T^2) score tensor
would blow HBM. This prints fwd+bwd ms for both paths across T so the
threshold is a measured number, not a guess.

Methodology matches benchmarks/flash_bwd_bench.py: K grad steps scanned
inside ONE jit (the carry chains iterations so nothing is elided or
overlapped), one device sync at the end.

Run: python -m benchmarks.attn_crossover
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.layers.attention import (
    scaled_dot_product_attention)
from deeplearning4j_tpu.ops.pallas_kernels import flash_attention


def bench(fn, q, k, v, steps=20, reps=3):
    grad = jax.grad(lambda q, k, v: jnp.sum(
        fn(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2))

    def body(carry, _):
        q, k, v = carry
        dq, dk, dv = grad(q, k, v)
        # chain the carry so scan iterations are sequential
        return (q + 0.0 * dq, k + 0.0 * dk, v + 0.0 * dv), None

    @jax.jit
    def run(q, k, v):
        (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=steps)
        return jnp.float32(jnp.sum(q))

    float(run(q, k, v))  # compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(run(q, k, v))
        best = min(best, (time.perf_counter() - t0) / steps * 1e3)
    return best


if __name__ == "__main__":
    h, dh = 12, 64
    for t, batch in ((128, 32), (128, 128), (256, 64), (512, 32),
                     (1024, 16), (2048, 8), (4096, 4)):
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.normal(size=(batch, t, h, dh)),
                               jnp.bfloat16) for _ in range(3))
        ms_x = bench(scaled_dot_product_attention, q, k, v)
        ms_f = bench(flash_attention, q, k, v)
        print(f"T={t:5d} batch={batch:3d}  xla {ms_x:8.3f} ms   "
              f"flash {ms_f:8.3f} ms   winner: "
              f"{'xla' if ms_x < ms_f else 'flash'}", flush=True)
