"""Shared interleaved-A/B timing harness.

Three benches (serving, generation, neighbors) independently grew the
same measurement discipline: run the arms INTERLEAVED — one sample per
arm per round, the arm order rotating each round so machine drift
(thermal throttle, page cache, GC pauses) lands on every arm equally
instead of biasing whichever arm runs last — discard warmup rounds,
and headline the MEDIAN across rounds (robust to one noisy round) with
p50/p99 client latencies from a LatencyRing. This module is that
discipline extracted once; the three benches and the autotune sweep
engine (benchmarks/autotune.py) all import it.

The helpers are deliberately shape-agnostic: an "arm" is any callable
of the round index returning one sample (throughput, qps, a wall
time). What the sample means — and whether bigger is better — stays
with the caller.
"""

from __future__ import annotations

import statistics
from typing import Any, Callable, Dict, List, Mapping, Sequence


def interleaved(arms: Mapping[str, Callable[[int], Any]], rounds: int,
                *, warmup: int = 0, rotate: bool = True
                ) -> Dict[str, List[Any]]:
    """Run every arm once per round, interleaved.

    ``arms`` maps arm name -> callable(round_index) -> sample. With
    ``rotate`` (the default) the arm order shifts by one each round —
    the neighbors-bench rotation — so slow drift is amortized across
    arms rather than accumulating on the last one. The first ``warmup``
    rounds execute fully (they warm caches, allocators, branch
    predictors) but their samples are dropped from the result.

    Returns arm name -> list of ``rounds`` samples, in round order.
    """
    if rounds < 1:
        raise ValueError("rounds must be >= 1")
    samples: Dict[str, List[Any]] = {name: [] for name in arms}
    order = list(arms)
    for r in range(warmup + rounds):
        if rotate:
            cut = r % len(order)
            rotation = order[cut:] + order[:cut]
        else:
            rotation = order
        for name in rotation:
            s = arms[name](r)
            if r >= warmup:
                samples[name].append(s)
    return samples


def median_of(samples: Mapping[str, Sequence[float]]) -> Dict[str, float]:
    """Median per arm — the headline number of every interleaved A/B."""
    return {name: statistics.median(vals)
            for name, vals in samples.items()}


def summarize(values: Sequence[float]) -> Dict[str, float]:
    """Median / p50 / p99 / n over raw samples, for sweeps that time
    cells directly instead of through a LatencyRing."""
    if not values:
        return {"n": 0.0}
    ordered = sorted(values)
    n = len(ordered)
    p99 = ordered[min(n - 1, int(0.99 * n))]
    return {"n": n * 1.0, "median": statistics.median(ordered),
            "p50": statistics.median(ordered), "p99": p99}


def fmt_quantiles(ring) -> str:
    """One-line p-quantile table from a LatencyRing (seconds -> ms)."""
    q = ring.quantiles()
    return "  ".join(f"p{int(k * 100)}={v * 1e3:7.2f}ms"
                     for k, v in sorted(q.items()))
