"""Hardware profile of the ResNet50 train step (round 3).

Captures a real device trace via jax.profiler (works on the tunneled
TPU), parses the xplane proto, and prints:
  * the authoritative device-side step time (XLA Modules line),
  * per-op-category leaf aggregation (where each ms goes),
  * achieved GB/s for the top data-movement ops (physical layout bytes
    from the HLO shapes ÷ measured per-op device time).

This replaces round 2's host-clock + logical-cost-analysis methodology,
which over-estimated step time (the "133 TFLOP/s matmul roofline" was a
host-sync artifact; the profiler-measured rate is 183 TFLOP/s, 93% of
the chip's 202.7 TFLOP/s peak) — VERDICT r2 weak #1.

Usage: python benchmarks/profile_hw.py [fused] [batch]
"""

import collections
import glob
import os
import re
import sys
import tempfile

import numpy as np

DT = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
      "s8": 1, "u8": 1}


def shape_bytes(txt: str) -> int:
    tot = 0
    for m in re.finditer(r"(f32|bf16|f16|s32|u32|s8|u8|pred)\[([\d,]*)\]",
                         txt):
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        tot += n * DT[m.group(1)]
    return tot


def capture_bert(batch: int, k: int, outdir: str, dtype: str):
    """Imported-BERT fine-tune step (BASELINE config 3 training half):
    the exact baseline_suite.bert_finetune graph — built by the SAME
    builder (baseline_suite.build_bert_finetune) — profiled with a
    device trace."""
    import jax
    import jax.random as jrandom
    from benchmarks.baseline_suite import build_bert_finetune

    ft, steps_fn, feats, ys = build_bert_finetune(
        seq=128, batch=batch, k=k, dtype=dtype)
    key = jrandom.PRNGKey(0)
    ts = ft.train_state
    ts, losses = steps_fn(ts, feats, (ys,), None, None, key)
    float(np.asarray(losses[-1]))
    with jax.profiler.trace(outdir):
        ts, losses = steps_fn(ts, feats, (ys,), None, None,
                              jrandom.fold_in(key, 1))
        float(np.asarray(losses[-1]))


def capture_lstm(batch: int, k: int, outdir: str, dtype: str):
    """TextGenerationLSTM train step (BASELINE config) under a device
    trace — same graph as baseline_suite.lstm via the shared builder."""
    import jax
    import jax.random as jrandom
    from benchmarks.baseline_suite import build_textgen_lstm

    model, steps_fn, xs, ys = build_textgen_lstm(
        seq=128, batch=batch, k=k, dtype=dtype)
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    float(np.asarray(losses[-1]))
    with jax.profiler.trace(outdir):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, 1))
        float(np.asarray(losses[-1]))


def capture_inception(batch: int, k: int, outdir: str, dtype: str):
    """Imported-InceptionV3 fine-tune step (BASELINE config 3 training
    half) under a device trace — same graph as
    baseline_suite.inception_train via the shared builder. ``dtype`` is
    accepted for CLI uniformity; the builder's FineTuneConfiguration
    fixes bf16 compute (the shipped benchmark config)."""
    import jax
    import jax.random as jrandom
    from benchmarks.baseline_suite import build_inception_finetune

    model, steps_fn, xs, ys = build_inception_finetune(batch, k)
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    float(np.asarray(losses[-1]))
    with jax.profiler.trace(outdir):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, 1))
        float(np.asarray(losses[-1]))


def capture(mode: str, batch: int, k: int, outdir: str):
    import jax
    import jax.numpy as jnp
    import jax.random as jrandom
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Nesterovs
    from deeplearning4j_tpu.zoo.models import ResNet50, VGG16

    if mode == "vgg":
        model = VGG16(num_classes=200, height=64, width=64, channels=3,
                      compute_dtype="bfloat16").init()

        def loss_fn(params, mstate, feats, labels, fmask, lmask, rng,
                    it):
            # MultiLayerNetwork _loss takes raw arrays
            return model._loss(params, mstate, feats, labels, fmask,
                               lmask, rng, it)
    else:
        model = ResNet50(
            num_classes=200, height=64, width=64, channels=3,
            compute_dtype="bfloat16", fused_blocks=mode != "unfused",
            fused_impl="xla" if mode == "gram" else "pallas",
            updater=Nesterovs(1e-2, 0.9)).init()

        def loss_fn(params, mstate, feats, labels, fmask, lmask, rng,
                    it):
            return model._loss(params, mstate, (feats,), (labels,),
                               fmask, lmask, rng, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3))
                    .astype(np.float32))
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), rng.integers(0, 200, batch)] = 1.0
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (k, batch, 200))
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    float(np.asarray(losses[-1]))
    with jax.profiler.trace(outdir):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, 1))
        float(np.asarray(losses[-1]))


def analyze(outdir: str, n_steps: int):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    path = sorted(glob.glob(outdir + "/plugins/profile/*/*.xplane.pb"))[-1]
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())
    for p in xs.planes:
        if p.name != "/device:TPU:0":
            continue
        emeta = {kk: v.name for kk, v in p.event_metadata.items()}
        for line in p.lines:
            if line.name == "XLA Modules":
                best = max((ev for ev in line.events),
                           key=lambda e: e.duration_ps)
                print(f"device step time: "
                      f"{best.duration_ps / 1e9 / n_steps:.3f} ms "
                      f"({emeta.get(best.metadata_id, '?')[:40]})")
            if line.name != "XLA Ops":
                continue
            agg = collections.Counter()
            per = collections.Counter()
            for ev in line.events:
                n = emeta.get(ev.metadata_id, "?")
                m = re.match(r"%([a-zA-Z0-9_\-\.]+) =", n)
                op = m.group(1) if m else n[:40]
                base = re.sub(r"[\.\d]+$", "", op)
                if base in ("while", "conditional", "call"):
                    continue
                agg[base] += ev.duration_ps
                per[ev.metadata_id] += ev.duration_ps
            total = sum(agg.values())
            print(f"leaf total {total / 1e9 / n_steps:.3f} ms/step")
            for b, ps in agg.most_common(14):
                print(f"  {b:36s} {ps / 1e9 / n_steps:8.4f} ms/step")
            print("top ops w/ achieved GB/s (operand+result layout "
                  "bytes / measured time):")
            rows = sorted(per.items(), key=lambda kv: -kv[1])[:10]
            for mid, ps in rows:
                n = emeta.get(mid, "?")
                by = shape_bytes(n)
                t = ps / 1e12 / n_steps
                print(f"  {ps / 1e9 / n_steps:7.4f} ms {by / 1e6:7.1f} MB"
                      f" {by / 1e9 / t if t else 0:6.0f} GB/s  {n[:80]}")


if __name__ == "__main__":
    # modes: unfused (default) | fused (pallas blocks) | gram (xla
    # blocks + Gram stats) | vgg | bert|lstm|inception [batch] [f32|bf16]
    # For the lstm mode, DL4J_LSTM_IMPL=fused|scan selects the
    # recurrence implementation (ops/pallas_lstm dispatch) so the fused
    # kernel's per-tick time can be profiled against the scan's.
    mode = sys.argv[1] if len(sys.argv) > 1 else "unfused"
    if mode not in ("unfused", "fused", "gram", "vgg", "bert", "lstm",
                    "inception"):
        sys.exit(f"unknown mode {mode!r}: expected "
                 "unfused|fused|gram|vgg|bert|lstm|inception "
                 "[batch] [f32|bf16]")
    # host-side span trace (observe/tracer.py) rides along with the
    # device xplane capture: build/compile/capture/analyze phases land
    # in <outdir>/host_trace.json, loadable in Perfetto / chrome://tracing
    from deeplearning4j_tpu.observe import SpanTracer
    tracer = SpanTracer()
    if mode in ("bert", "lstm", "inception"):
        batch = int(sys.argv[2]) if len(sys.argv) > 2 else (
            {"bert": 32, "lstm": 256, "inception": 64}[mode])
        dtype = sys.argv[3] if len(sys.argv) > 3 else "f32"
        if dtype not in ("f32", "bf16"):
            sys.exit(f"unknown dtype {dtype!r}: expected f32|bf16")
        k = 8
        outdir = tempfile.mkdtemp(prefix="dl4j_hwprof_")
        with tracer.span("capture", cat="profile", mode=mode,
                         batch=batch, k=k):
            {"bert": capture_bert, "lstm": capture_lstm,
             "inception": capture_inception}[mode](batch, k, outdir,
                                                   dtype)
        print(f"trace: {outdir}")
        with tracer.span("analyze", cat="profile"):
            analyze(outdir, k)
        tracer.save(outdir + "/host_trace.json")
        print(f"host trace: {outdir}/host_trace.json")
        sys.exit(0)
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else (
        512 if mode == "vgg" else 256)
    k = 64
    outdir = tempfile.mkdtemp(prefix="dl4j_hwprof_")
    with tracer.span("capture", cat="profile", mode=mode, batch=batch,
                     k=k):
        capture(mode, batch, k, outdir)
    print(f"trace: {outdir}")
    with tracer.span("analyze", cat="profile"):
        analyze(outdir, k)
    tracer.save(outdir + "/host_trace.json")
    print(f"host trace: {outdir}/host_trace.json")
