"""Serving engine: pipelined vs blocking dispatcher under load.

The claim under test (parallel/serving.py): the seed dispatcher's fixed
aggregation window + inline host-sync fetch put a floor of
``timeout_ms + device_roundtrip`` under every request; the pipelined
engine's backpressure aggregation (coalesce only while the device is
busy) and completion-thread fetch remove both, so closed-loop
throughput rises and the latency tail collapses. On a 1-core CPU box
the window elimination dominates; on a real accelerator the
dispatch/fetch overlap is the bigger half — PERF_ANALYSIS r8 records
the decomposition.

Two load shapes:
- **closed-loop**: N client threads, each issuing its next request the
  moment the previous answer lands — throughput-bound, the arm ratio is
  the A/B headline.
- **open-loop**: Poisson arrivals at a target rate, submitted without
  waiting — latency-bound; the p50/p95/p99 table is the story (a
  closed loop can't see coordinated omission).

Arms alternate per round (A/B interleaved, like input_pipeline.py) so
machine-load drift hits both equally.

Usage:
    python benchmarks/serving.py                   # timed A/B + curve
    python benchmarks/serving.py --rate 500        # open-loop point
    python benchmarks/serving.py --smoke           # CI gate: bitwise vs
        # direct model.output, zero recompiles after warmup, pipelined
        # >= 1.3x blocking closed-loop
"""

from __future__ import annotations

import argparse
import random
import statistics
import threading
import time

import numpy as np

from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.serving import ServingEngine

FEATURES = 128


def build_model(seed: int = 7, width: int = 1024):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=width))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    return MultiLayerNetwork(conf).init()


def make_engine(model, *, pipelined: bool, session: str,
                batch_limit: int = 32, timeout_ms: float = 5.0,
                replicas=1) -> ServingEngine:
    # isolated registry per arm: the A/B must not share counters
    return ServingEngine(
        model, batch_limit=batch_limit, timeout_ms=timeout_ms,
        pipelined=pipelined, replicas=replicas,
        feature_shape=(FEATURES,), registry=MetricsRegistry(),
        session_id=session)


def closed_loop(engine: ServingEngine, n_clients: int, n_requests: int,
                req_size: int, seed: int = 0):
    """N clients, each firing its next request on completion. Returns
    (throughput req/s, LatencyRing of client-observed latencies)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(req_size, FEATURES)).astype(np.float32)
    ring = LatencyRing(capacity=n_clients * n_requests)
    barrier = threading.Barrier(n_clients + 1)
    errors = []

    def client():
        barrier.wait()
        try:
            for _ in range(n_requests):
                t0 = time.perf_counter()
                engine.output(x)
                ring.record(time.perf_counter() - t0)
        except Exception as e:      # surface, don't hang the barrier
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return (n_clients * n_requests) / wall, ring


def open_loop(engine: ServingEngine, rate_hz: float, duration_s: float,
              req_size: int, seed: int = 0):
    """Poisson arrivals at ``rate_hz``, submitted without waiting for
    completions. Returns (achieved req/s, LatencyRing)."""
    rng = np.random.default_rng(seed)
    arrival = random.Random(seed)
    x = rng.normal(size=(req_size, FEATURES)).astype(np.float32)
    ring = LatencyRing(capacity=int(rate_hz * duration_s) + 64)
    pending = []
    t_start = time.perf_counter()
    deadline = t_start + duration_s
    while time.perf_counter() < deadline:
        t0 = time.perf_counter()
        f = engine.submit(x)
        f.add_done_callback(
            lambda _f, t0=t0: ring.record(time.perf_counter() - t0))
        pending.append(f)
        time.sleep(arrival.expovariate(rate_hz))
    for f in pending:
        f.result()
    wall = time.perf_counter() - t_start
    return len(pending) / wall, ring


def _fmt_quantiles(ring: LatencyRing) -> str:
    q = ring.quantiles()
    return "  ".join(f"p{int(k * 100)}={v * 1e3:7.2f}ms"
                     for k, v in sorted(q.items()))


def run_timed(args) -> int:
    model = build_model(width=args.width)
    arms = {}
    for name, pipelined in (("blocking", False), ("pipelined", True)):
        arms[name] = make_engine(
            model, pipelined=pipelined, session=name,
            batch_limit=args.batch_limit, timeout_ms=args.timeout_ms,
            replicas=args.replicas)
    try:
        tput = {name: [] for name in arms}
        rings = {name: LatencyRing(capacity=1 << 16) for name in arms}
        for r in range(args.rounds):
            for name, eng in arms.items():
                t, ring = closed_loop(eng, args.clients, args.requests,
                                      args.req_size, seed=r)
                tput[name].append(t)
                for v in ring.snapshot():
                    rings[name].record(v)
        med = {n: statistics.median(ts) for n, ts in tput.items()}
        print(f"closed-loop: {args.clients} clients x {args.requests} "
              f"requests x{args.req_size}, median of {args.rounds} "
              "rounds:")
        for name in arms:
            print(f"  {name:9s} {med[name]:9.1f} req/s   "
                  f"{_fmt_quantiles(rings[name])}")
        speedup = med["pipelined"] / med["blocking"]
        print(f"pipelined speedup: {speedup:.2f}x")

        if args.rate:
            t, ring = open_loop(arms["pipelined"], args.rate,
                                args.open_duration, args.req_size)
            print(f"open-loop (Poisson {args.rate:.0f} req/s target): "
                  f"{t:9.1f} req/s achieved   {_fmt_quantiles(ring)}")
        for name, eng in arms.items():
            eng.assert_warm()
        if args.assert_speedup and speedup < args.assert_speedup:
            print(f"FAIL: pipelined speedup {speedup:.2f}x below the "
                  f"{args.assert_speedup:.2f}x floor")
            return 1
        return 0
    finally:
        for eng in arms.values():
            eng.shutdown()


def run_smoke(args) -> int:
    """CI gate: (1) serving output bitwise-equal to direct
    ``model.output`` across request sizes (including padded, split and
    co-batched ones); (2) zero recompiles after the warmup sweep,
    watchdog-asserted; (3) pipelined >= 1.3x blocking closed-loop
    throughput. The margin measured on a 1-core CPU box is ~10x
    (PERF_ANALYSIS r8), so the 1.3x floor keeps noise headroom."""
    model = build_model(width=64)
    rng = np.random.default_rng(0)
    eng = make_engine(model, pipelined=True, session="smoke",
                      batch_limit=16)
    try:
        for n in (1, 2, 3, 5, 8, 16, 37):   # 37 > batch_limit: splits
            x = rng.normal(size=(n, FEATURES)).astype(np.float32)
            got = eng.output(x)
            want = np.asarray(model.output(x))
            if got.shape != want.shape or not np.array_equal(got, want):
                print(f"FAIL: serving output diverged from direct "
                      f"model.output at request size {n} "
                      f"(max abs diff "
                      f"{np.max(np.abs(got - want)):.3e})")
                return 1
        # concurrent co-batched requests must slice back bitwise too
        t, _ring = closed_loop(eng, 4, 25, 2)
        got = eng.output(rng.normal(size=(3, FEATURES))
                         .astype(np.float32))
        eng.assert_warm()       # zero recompiles after warmup
        stats = eng.stats()
    finally:
        eng.shutdown()

    # A/B throughput gate on fresh engines (isolated counters)
    arms = {}
    for name, pipelined in (("blocking", False), ("pipelined", True)):
        arms[name] = make_engine(model, pipelined=pipelined,
                                 session=f"smoke-{name}", batch_limit=16)
    try:
        tput = {name: [] for name in arms}
        rings = {name: LatencyRing(capacity=1 << 14) for name in arms}
        for r in range(3):
            for name, e in arms.items():
                tp, ring = closed_loop(e, 4, 30, 1, seed=r)
                tput[name].append(tp)
                for v in ring.snapshot():
                    rings[name].record(v)
        med = {n: statistics.median(ts) for n, ts in tput.items()}
        speedup = med["pipelined"] / med["blocking"]
        for name in arms:
            print(f"  {name:9s} {med[name]:9.1f} req/s   "
                  f"{_fmt_quantiles(rings[name])}")
        arms["pipelined"].assert_warm()
    finally:
        for e in arms.values():
            e.shutdown()

    if speedup < 1.3:
        print(f"FAIL: pipelined speedup {speedup:.2f}x below the 1.3x "
              "floor")
        return 1
    print(f"serving smoke: bitwise vs direct output, "
          f"{stats['recompiles_after_warmup']} recompiles after warmup, "
          f"pipelined {speedup:.2f}x blocking")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--requests", type=int, default=100,
                    help="requests per client per round")
    ap.add_argument("--req-size", type=int, default=1,
                    help="examples per request")
    ap.add_argument("--rounds", type=int, default=5,
                    help="interleaved A/B rounds")
    ap.add_argument("--batch-limit", type=int, default=32)
    ap.add_argument("--timeout-ms", type=float, default=5.0,
                    help="aggregation upper bound (the blocking arm's "
                    "fixed window)")
    ap.add_argument("--replicas", default=1,
                    help="device replicas (int or 'auto')")
    ap.add_argument("--width", type=int, default=1024,
                    help="hidden width of the benchmark model")
    ap.add_argument("--rate", type=float, default=None,
                    help="add an open-loop (Poisson) point at this "
                    "req/s target")
    ap.add_argument("--open-duration", type=float, default=5.0,
                    help="open-loop measurement window, seconds")
    ap.add_argument("--assert-speedup", type=float, default=None,
                    help="exit 1 when pipelined/blocking falls below")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: bitwise outputs, zero post-warmup "
                    "recompiles, >=1.3x closed-loop")
    args = ap.parse_args(argv)
    if args.replicas != "auto":
        args.replicas = int(args.replicas)
    return run_smoke(args) if args.smoke else run_timed(args)


if __name__ == "__main__":
    import sys
    sys.exit(main())
